"""Tiered KV memory: the host-RAM spill tier under the paged trie.

The contract under test, strongest first:

  * re-admitted blocks are BIT-IDENTICAL to cold prefill — greedy and
    seeded sampling, bf16 and int8 KV, single-device and tp=2, all
    three families (the H2D restore writes back the exact rows the
    D2H spill took out, so the block-table gather sees the same
    floats either way);
  * eviction never stalls decode: the spill is an async D2H handoff
    to a background drain, and a wedged drain degrades evictions to
    drop-on-evict (bounded queue) while every stream still finishes;
  * an injected D2H fault ("engine.spill") degrades that one
    eviction to a plain drop — counter bumped, serving uninterrupted,
    never a crashed engine;
  * N-cycle spill/re-admit churn leaks nothing: host-pool bytes,
    device-pool accounting, refcounts and reservations all return to
    baseline;
  * the tier budget is part of the effective KV geometry, so a gang
    follower with a drifted budget fails the welcome comparison.
"""
import dataclasses
import random
import threading
import time
import queue as queue_lib

import jax
import jax.numpy as jnp
import pytest

from skypilot_tpu.models import gemma, llama, mixtral
from skypilot_tpu.serve import decode_engine
from skypilot_tpu.serve import gang_replica
from skypilot_tpu.serve import kv_pool
from skypilot_tpu.serve.decode_engine import DecodeEngine
from skypilot_tpu.utils import fault_injection


def _tiny(family="llama"):
    if family == "mixtral":
        return mixtral, mixtral.MixtralConfig.tiny()
    if family == "gemma":
        return gemma, gemma.GemmaConfig.tiny(vocab_size=128)
    return llama, llama.LlamaConfig.tiny(vocab_size=128)


def _drive(engine, rounds=200):
    """Step an UNSTARTED engine deterministically until idle."""
    for _ in range(rounds):
        engine._admit()
        did = engine._prefill_one()
        did = engine._decode_step() or did
        if not did and not engine._waiting:
            return
    raise AssertionError("engine did not quiesce")


def _drain_to_host(eng, timeout=30.0):
    """Force every evictable device block into the host tier (each
    eviction must SPILL, not drop) and wait for the D2H drains to
    land so the next match is a pure host-tier hit."""
    while True:
        out = eng.prefix_cache.evict_one()
        if not out:
            break
        assert out == "spilled", out
    deadline = time.monotonic() + timeout
    while eng.spill_in_flight() > 0:
        assert time.monotonic() < deadline, "spill drain never landed"
        time.sleep(0.005)


# ================================================ host pool accounting
def test_host_block_pool_accounting_budget_and_inflight():
    import numpy as np
    pool = kv_pool.HostBlockPool(budget_bytes=3 * 64)
    blk = {"k": np.zeros(16, np.float32)}       # 64 bytes per entry

    # In-flight protocol: has() counts a kicked-but-unlanded spill
    # (the trie must keep the node), get() does not (admission cannot
    # restore bytes that are not on host yet).
    pool.mark_inflight(("a",))
    assert pool.has(("a",)) and pool.get(("a",)) is None
    pool.put(("a",), dict(blk))
    assert pool.stats()["inflight"] == 0        # landing clears it
    assert pool.get(("a",)) is not None
    assert pool.stats()["rehits"] == 1

    # LRU within the byte budget: 3 entries fit, the 4th drops the
    # least-recently-USED (a was just rehit, so b goes first).
    pool.put(("b",), dict(blk))
    pool.put(("c",), dict(blk))
    pool.get(("a",))
    pool.put(("d",), dict(blk))
    assert not pool.has(("b",))
    assert pool.has(("a",)) and pool.has(("c",)) and pool.has(("d",))
    assert pool.stats()["lru_dropped"] == 1
    assert pool.stats()["bytes"] == 3 * 64

    # An entry bigger than the whole budget is refused outright
    # (never evict the world for one oversized block).
    assert not pool.put(("big",), {"k": np.zeros(128, np.float32)})
    assert pool.has(("a",))                     # nothing was evicted

    pool.discard(("a",))
    assert not pool.has(("a",))
    assert pool.stats()["blocks"] == 2


# ======================================= bit-parity: spill -> re-admit
@pytest.mark.parametrize("family", ["llama", "mixtral", "gemma"])
def test_tier_readmit_bit_identical_cold_prefill(family):
    """Greedy AND seeded streams after a full spill/re-admit cycle
    equal the cold streams token-for-token (and the greedy one equals
    the fixed-path reference), with the warm request measurably
    cheaper in prefill chunks."""
    mdl, cfg = _tiny(family)
    params = mdl.init(cfg, jax.random.key(0))
    rng = random.Random(1)
    pg = [rng.randint(1, cfg.vocab_size - 1) for _ in range(17)]
    ps = [rng.randint(1, cfg.vocab_size - 1) for _ in range(19)]
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True,
                       prefix_cache_mb=8).start()
    try:
        cold_g = eng.submit(pg, max_tokens=4)
        cold_s = eng.submit(ps, max_tokens=4, temperature=0.9, seed=17)
        cold_g_toks = cold_g.result(timeout=300.0)
        cold_s_toks = cold_s.result(timeout=300.0)

        _drain_to_host(eng)
        assert eng.prefix_cache.stats()["host_chunks"] >= 4

        warm_g = eng.submit(pg, max_tokens=4)
        warm_s = eng.submit(ps, max_tokens=4, temperature=0.9, seed=17)
        assert warm_g.result(timeout=300.0) == cold_g_toks
        assert warm_s.result(timeout=300.0) == cold_s_toks
        ref = mdl.decode(cfg, params, jnp.asarray([pg], jnp.int32),
                         jnp.int32(len(pg)), 4, len(pg) + 4)
        assert cold_g_toks == [int(t) for t in ref[0]]
        assert warm_g.cached_prompt_tokens == 16
        assert warm_s.cached_prompt_tokens == 16
        assert warm_g.prefill_chunks < cold_g.prefill_chunks
        tier = eng.host_tier_stats()
        assert tier["readmitted_blocks"] >= 4
        assert tier["rehits"] >= 4
    finally:
        eng.shutdown()


def test_tier_readmit_bit_identical_int8_kv():
    """The quantized pool spills int8 payloads + scale leaves and
    re-admits them bit-identically — transfers at half the bf16
    bytes, same streams."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    prompt = [int(t) for t in jax.random.randint(
        jax.random.key(2), (21,), 1, 128)]
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, kv_quant=True,
                       prefix_cache_mb=8).start()
    try:
        cold = eng.submit(prompt, max_tokens=5)
        cold_toks = cold.result(timeout=300.0)
        seeded_cold = eng.submit(prompt, max_tokens=5,
                                 temperature=0.8,
                                 seed=3).result(timeout=300.0)
        _drain_to_host(eng)
        warm = eng.submit(prompt, max_tokens=5)
        assert warm.result(timeout=300.0) == cold_toks
        assert eng.submit(prompt, max_tokens=5, temperature=0.8,
                          seed=3).result(timeout=300.0) == seeded_cold
        assert warm.cached_prompt_tokens == 16
        assert eng.host_tier_stats()["readmitted_blocks"] >= 2
    finally:
        eng.shutdown()


def test_tier_readmit_bit_identical_tp2():
    """The tp=2 sharded engine (pool sharded by cache_specs) spills
    and re-admits through the same seam: the sharded slices land on
    host, restore into the sharded pool, and the warm stream stays
    bit-identical in f32."""
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=128),
                              dtype=jnp.float32)
    params = llama.init(cfg, jax.random.key(0))
    topo = gang_replica.ReplicaTopology(hosts=1, ici_axes={"tp": 2})
    mesh, rules = gang_replica.build_mesh(topo)
    sparams = gang_replica.shard_params(cfg, params, mesh, rules)
    prompt = [int(t) for t in jax.random.randint(
        jax.random.key(4), (18,), 1, 128)]
    eng = DecodeEngine(cfg, sparams, slots=2, max_seq=64,
                       prefill_chunk=8, mesh=mesh, rules=rules,
                       paged=True, prefix_cache_mb=8).start()
    try:
        cold = eng.submit(prompt, max_tokens=5)
        cold_toks = cold.result(timeout=600.0)
        _drain_to_host(eng)
        warm = eng.submit(prompt, max_tokens=5)
        assert warm.result(timeout=600.0) == cold_toks
        assert warm.cached_prompt_tokens == 16
        assert eng.host_tier_stats()["readmitted_blocks"] >= 2
    finally:
        eng.shutdown()


# ============================================= churn leaks nothing
def test_tier_churn_accounting_identity():
    """20 seeded admit/evict/rehit cycles over a fixed prompt set:
    after the warm-up cycle populates the (inclusive) host tier, every
    later cycle must return host bytes/blocks, device free-list,
    reservations and refcounts to the same baseline."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, prefix_cache_mb=8)
    rng = random.Random(11)
    prompts = [[rng.randint(1, 127) for _ in range(rng.randint(17, 25))]
               for _ in range(4)]

    def cycle():
        for p in prompts:
            eng.submit(p, max_tokens=rng.randint(1, 3))
            _drive(eng)
        _drain_to_host(eng)

    cycle()                                    # warm-up fills the tier
    base = eng.host_tier_stats()
    for _ in range(20):
        cycle()
        now = eng.host_tier_stats()
        assert now["bytes"] == base["bytes"]
        assert now["blocks"] == base["blocks"]
        assert now["lru_dropped"] == base["lru_dropped"] == 0
        assert now["evict_drops"] == 0
    pool = eng._pool
    # Everything is host-resident: the device pool is fully free, no
    # reservations or pins are outstanding, and the trie still spans
    # the full prompt set (host-side).
    assert pool.free_blocks() == pool.usable_blocks
    assert pool._reserved == 0
    assert all(n.refs == 0 for n in eng.prefix_cache.nodes())
    assert all(n.block < 0 for n in eng.prefix_cache.nodes())
    stats = eng.prefix_cache.stats()
    assert stats["host_chunks"] == stats["chunks"] == base["blocks"]
    eng.shutdown()


# ==================================== decode never blocks on a spill
def test_decode_never_blocks_on_wedged_spill_drain():
    """Monkeypatch bomb: the drain thread is frozen mid-store and the
    spill queue shrunk to 2, so in-flight spills pile up and the
    bounded queue fills. Every stream must still complete — evictions
    past the backlog degrade to drops, and the compute loop never
    waits on the host tier."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, prefix_cache_mb=64)
    eng._spill_q = queue_lib.Queue(maxsize=2)
    unfreeze = threading.Event()
    orig_put = eng._host_pool.put

    def frozen_put(path, arrays):
        unfreeze.wait(timeout=60.0)
        return orig_put(path, arrays)

    eng._host_pool.put = frozen_put
    eng.start()
    rng = random.Random(13)
    try:
        reqs = [eng.submit([rng.randint(1, 127) for _ in range(17)],
                           max_tokens=2) for _ in range(12)]
        for r in reqs:
            assert len(r.result(timeout=120.0)) == 2
        stats = eng.prefix_cache.stats()
        assert stats["spills"] >= 1             # tier was exercised...
        assert stats["drops"] >= 1              # ...and backlog dropped
        assert eng.spill_in_flight() >= 1       # while still wedged
    finally:
        unfreeze.set()
        eng.shutdown()


# ================================================ fault seam degrades
def test_injected_spill_fault_degrades_to_drop():
    """engine.spill firing makes THAT eviction a plain drop-on-evict:
    outcome counted, the prefix re-prefills cold, the engine never
    crashes."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    prompt = list(range(1, 18))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True,
                       prefix_cache_mb=8).start()
    try:
        cold_toks = eng.submit(prompt,
                               max_tokens=3).result(timeout=300.0)
        with fault_injection.inject("engine.spill"):
            while True:
                out = eng.prefix_cache.evict_one()
                if not out:
                    break
                assert out == "dropped"
        stats = eng.prefix_cache.stats()
        assert stats["drops"] == 2 and stats["spills"] == 0
        assert eng.host_tier_stats()["blocks"] == 0
        # Serving continues: the dropped prefix simply prefills cold
        # again (and spills cleanly once the fault is disarmed).
        again = eng.submit(prompt, max_tokens=3)
        assert again.result(timeout=300.0) == cold_toks
        assert again.cached_prompt_tokens == 0
        _drain_to_host(eng)
        assert eng.prefix_cache.stats()["spills"] >= 1
    finally:
        eng.shutdown()


# ======================================= geometry rides the handshake
def test_tier_budget_is_kv_geometry():
    """host_mb is part of the effective KV geometry dict the gang
    welcome compares — a follower with a drifted tier budget produces
    a different dict and dies at join (the comparison is pinned fatal
    by test_paged_kv's welcome test)."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    geo = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, prefill_chunk=8, paged=True,
        host_cache_mb=8.0)
    assert geo["host_mb"] == 8.0
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, prefix_cache_mb=8)
    assert eng.kv_config() == geo
    drifted = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, prefill_chunk=8, paged=True,
        host_cache_mb=64.0)
    assert drifted != geo
    # The dense path has no tier: the knob must not leak geometry.
    dense = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, prefill_chunk=8, paged=False,
        host_cache_mb=8.0)
    assert "host_mb" not in dense
    eng.shutdown()


def test_tier_off_by_zero_budget():
    """prefix_cache_mb=0 disables the tier: evictions drop like the
    pre-tier engine and the introspection surface reports empty."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, prefix_cache_mb=0)
    eng.submit(list(range(1, 18)), max_tokens=2)
    _drive(eng)
    assert eng.prefix_cache.evict_one() == "dropped"
    assert eng.host_tier_stats() == {}
    assert eng.spill_in_flight() == 0
    assert "host_mb" in eng.kv_config()         # geometry still pinned
    assert eng.kv_config()["host_mb"] == 0.0
    eng.shutdown()


# ==================================================== metrics surface
def test_tier_metrics_exposed():
    """Eviction outcomes, tier hits and the host gauges land in the
    process registry (and therefore replica /metrics + LB merge)."""
    from skypilot_tpu.observability import metrics as metrics_lib
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    evs = metrics_lib.REGISTRY.counter(
        "stpu_engine_kv_pool_evictions_total",
        labelnames=("outcome",))
    spilled_before = evs.labels(outcome="spilled").get()
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True,
                       prefix_cache_mb=8).start()
    try:
        prompt = list(range(20, 37))
        eng.submit(prompt, max_tokens=2).result(timeout=300.0)
        _drain_to_host(eng)
        eng.submit(prompt, max_tokens=2).result(timeout=300.0)
    finally:
        eng.shutdown()
    assert evs.labels(outcome="spilled").get() >= spilled_before + 2
    text = metrics_lib.render()
    assert "stpu_engine_kv_host_bytes" in text
    assert "stpu_engine_kv_host_blocks" in text
    assert 'stpu_engine_kv_tier_hits_total{tier="host"}' in text
    assert "stpu_engine_kv_host_readmitted_blocks_total" in text
