"""The sshd-free worker transport (agent/exec_server.py + exec_client)
and the token-authenticated direct-connect gang coordinator.

VERDICT r3 weak #5: kubernetes multi-host gangs required an
sshd-capable image; the exec agent removes the constraint — any image
with python3 works.
"""
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from skypilot_tpu.agent import exec_client, exec_server, native

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOKEN = "tok" + "0" * 29


@pytest.fixture
def server(tmp_path):
    srv = exec_server.ExecServer(0, TOKEN, home=str(tmp_path))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


def test_exec_round_trip(server):
    import io
    out = io.BytesIO()
    rc = exec_client.run(
        "127.0.0.1", server.port,
        b"export GREETING=hello-sekrit\n"
        b"echo \"$GREETING world\"\nexit 7\n", TOKEN, out=out)
    assert rc == 7
    assert b"hello-sekrit world" in out.getvalue()


def test_exec_bad_token_rejected(server):
    import io
    out = io.BytesIO()
    rc = exec_client.run("127.0.0.1", server.port, b"echo leaked\n",
                         "wrong" + "0" * 27, out=out)
    assert rc == 255
    assert b"leaked" not in out.getvalue()


def test_exec_client_death_kills_remote_command(server, tmp_path):
    """ssh-session semantics: killing the client (gang terminate path)
    drops the socket and the server kills the command's process group."""
    pid_file = tmp_path / "victim.pid"
    script = (f"echo $$ > {pid_file}\nsleep 300\n").encode()
    tok = tmp_path / "tok"
    tok.write_text(TOKEN)
    proc = subprocess.Popen(
        [sys.executable, "-m", "skypilot_tpu.agent.exec_client",
         "--host", "127.0.0.1", "--port", str(server.port),
         "--token-file", str(tok)],
        stdin=subprocess.PIPE, stdout=subprocess.DEVNULL,
        env={**os.environ, "PYTHONPATH": REPO_ROOT})
    proc.stdin.write(script)
    proc.stdin.close()
    deadline = time.time() + 15
    while not pid_file.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert pid_file.exists(), "remote command never started"
    victim = int(pid_file.read_text().strip())
    os.kill(victim, 0)  # alive
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=10)
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            os.kill(victim, 0)
        except ProcessLookupError:
            return  # killed - the point of the test
        time.sleep(0.2)
    pytest.fail("remote command survived client death")


def test_exec_token_never_in_argv(server, tmp_path, monkeypatch):
    """The gang driver's agent transport: env (secrets) and command ride
    the exec protocol, never the client argv."""
    from skypilot_tpu.agent import gang_exec
    captured = []
    real_popen = subprocess.Popen

    def spy(argv, **kw):
        # NOTE: subprocess is one shared module — the in-process exec
        # SERVER's own Popen also lands here; collect all.
        captured.append(argv)
        return real_popen(argv, **kw)

    monkeypatch.setattr(gang_exec.subprocess, "Popen", spy)
    log = tmp_path / "log"
    p = gang_exec._HostProc(
        {"kind": "agent", "ip": "127.0.0.1", "port": server.port},
        rank=1, cmd="echo agent-ran-$SECRET_V",
        env={"SECRET_V": "hunter2zzz"}, log_path=str(log),
        coord_token=TOKEN)
    assert p.wait() == 0
    for argv in captured:
        assert "hunter2zzz" not in " ".join(str(a) for a in argv)
    assert any("exec_client" in " ".join(str(a) for a in argv)
               for argv in captured)
    assert "agent-ran-hunter2zzz" in log.read_bytes().decode()


# ---------------------------------------------- token-auth coordinator
@pytest.mark.parametrize("force_py", [True, False])
def test_coordinator_token_mode(monkeypatch, force_py):
    """Direct-connect mode: network bind + token handshake; wrong token
    is rejected, right token barriers normally."""
    if force_py:
        monkeypatch.setenv("STPU_FORCE_PY_AGENT", "1")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_lib_tried", False)
    coord = native.Coordinator(2, heartbeat_timeout_ms=5000,
                               token=TOKEN)
    try:
        # Wrong token: never registers.
        with pytest.raises(OSError):
            native.Client("127.0.0.1", coord.port, 0, timeout_ms=1500,
                          token="bad" + "1" * 29)
        assert coord.registered_count == 0
        c0 = native.Client("127.0.0.1", coord.port, 0, token=TOKEN)
        c1 = native.Client("127.0.0.1", coord.port, 1, token=TOKEN)
        assert coord.wait_ready(5000) == 0
        results = {}

        def do_barrier(c, r):
            results[r] = c.barrier(0, 5000)

        ts = [threading.Thread(target=do_barrier, args=(c, r))
              for r, c in ((0, c0), (1, c1))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert results == {0: 0, 1: 0}
        c0.close()
        c1.close()
    finally:
        coord.close()


def test_gang_with_agent_worker_end_to_end(tmp_state_dir, tmp_path,
                                           monkeypatch):
    """Full 2-host gang: head ("exec" kind) + worker over the sshd-free
    agent transport, with the token-auth direct-connect coordinator
    gating both ranks at the barrier."""
    from skypilot_tpu.agent import gang_exec, job_lib

    head = tmp_path / "headhome"
    worker = tmp_path / "workerhome"
    for h in (head, worker):
        (h / ".stpu_agent").mkdir(parents=True)
        (h / ".stpu_agent" / "exec_token").write_text(TOKEN)
    monkeypatch.setenv("HOME", str(head))
    # The worker pod's exec agent, homed at the worker's dir.
    srv = exec_server.ExecServer(0, TOKEN, home=str(worker))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    # Workers import the package via the wheel; fake hosts via PYTHONPATH.
    monkeypatch.setenv("PYTHONPATH", REPO_ROOT + ":" +
                       os.environ.get("PYTHONPATH", ""))
    try:
        job_id = job_lib.add_job("t", "u", "ts", "")
        spec = {
            "job_id": job_id,
            "task_id": "t-1",
            "cluster_name": "c",
            "node_ips": ["127.0.0.1", "127.0.0.1"],
            "num_slices": 1,
            "hosts_per_slice": 2,
            "chips_per_host": 0,
            "envs": {"STPU_SKIP_HEALTH_PROBE": "1"},
            "run_cmd": "echo rank=$SKYPILOT_NODE_RANK > out.txt",
            "log_dir": str(head / "logs"),
            "hosts": [
                {"kind": "exec", "slice_index": 0},
                {"kind": "agent", "ip": "127.0.0.1", "port": srv.port,
                 "slice_index": 0},
            ],
            "agent_home": str(head),
        }
        rc = gang_exec.run_gang(spec)
        assert rc == 0, (head / "logs").glob("*")
        assert (head / "out.txt").read_text().strip() == "rank=0"
        assert (worker / "out.txt").read_text().strip() == "rank=1"
        assert job_lib.get_job(job_id, str(head))["status"] == \
            "SUCCEEDED"
    finally:
        srv.shutdown()
