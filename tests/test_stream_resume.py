"""Durable streams: LB mid-stream resume + engine resume admission.

The contract under test (ISSUE 19 tentpole):
  * while proxying a streaming /generate the LB journals every token
    event it forwards; when the UPSTREAM dies mid-stream (never the
    client) it re-picks a peer excluding every replica the request
    already burned, re-submits with the `resume: {emitted, pos}`
    extension, and splices the continuation into the SAME client
    stream — the client's bytes are bit-identical to an uninterrupted
    run, greedy and seeded alike;
  * a peer that ignores `resume` and replays from position 0 is
    deduped, with every replayed token VERIFIED against the journal
    (a divergent peer must abort, not corrupt the stream);
  * resumes are budgeted (STPU_LB_STREAM_RESUMES) and the journal is
    byte-capped (STPU_LB_RESUME_JOURNAL_MB) — exhaustion and eviction
    degrade to the plain upstream abort, never an unbounded promise;
  * the engine side: `resume.emitted` re-enters as a prompt extension
    and generation continues at the same absolute positions with the
    original seed (fold_in(seed, position) sampling), dense and paged,
    spec-on, so the splice really is bit-identical;
plus the game-day lever: fault point ``lb.stream`` kills a proxied
stream after K reads and the resume ladder heals it end to end.
"""
import http.client
import http.server
import json
import socket
import socketserver
import struct
import threading
import time
import types
import urllib.request

import pytest

from skypilot_tpu.observability import metrics
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve.load_balancing_policies import (
    LoadBalancingPolicy)
from skypilot_tpu.utils import fault_injection as fi


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


# ====================================================== stub LB stack
class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        pass    # mid-stream deaths are intentional here; keep CI quiet


def _tok(prompt, pos):
    """The stub's deterministic sampler: the token at absolute
    position ``pos`` is a pure function of (prompt, pos) — the same
    replica-independence the real engine gets from
    fold_in(seed, position), so any honest peer continues the exact
    stream the dead one was emitting."""
    return (sum(prompt) * 31 + pos * 7) % 997


class _Replica(http.server.BaseHTTPRequestHandler):
    """Stub replica speaking the serve_llm resume contract: honors
    `resume: {emitted, pos}` by emitting from the absolute position
    (acknowledged via X-STPU-Resume), or — with ``honor_resume`` off —
    replays from 0 like a pre-resume replica. ``abort_after`` drops
    the connection after N token events of THIS request (no [DONE]);
    ``token_offset`` simulates a divergent peer."""
    protocol_version = "HTTP/1.1"
    abort_after = None
    honor_resume = True
    token_offset = 0
    delay = 0.0
    hits = None         # list of (port, start_pos, honored)

    def log_message(self, *a):
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        req = json.loads(self.rfile.read(length) or b"{}")
        prompt = [int(t) for t in req["prompt"]]
        mt = int(req.get("max_tokens", 8))
        resume = req.get("resume")
        start, honored = 0, False
        if resume is not None and self.honor_resume:
            start, honored = int(resume["pos"]), True
        if self.hits is not None:
            self.hits.append((self.server.server_address[1], start,
                              honored))
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        if honored:
            self.send_header("X-STPU-Resume", str(start))
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        sent = 0
        for pos in range(start, mt):
            if self.delay:
                time.sleep(self.delay)
            if self.abort_after is not None and sent >= self.abort_after:
                self.wfile.flush()
                self.connection.close()
                return
            tok = _tok(prompt, pos) + self.token_offset
            lb_lib.write_chunk(
                self.wfile, f'data: {{"token": {tok}}}\n\n'.encode())
            sent += 1
        lb_lib.write_chunk(self.wfile, b"data: [DONE]\n\n")
        lb_lib.end_chunks(self.wfile)


class _OrderedPolicy(LoadBalancingPolicy):
    """First non-excluded URL in a fixed priority order — the tests
    need a deterministic initial pick (the failing replica) and a
    deterministic resume pick (the next peer)."""

    def __init__(self, urls):
        self._urls = list(urls)
        self.done = []

    def set_ready_replicas(self, urls):
        self._urls = list(urls)

    def select_replica(self, request=None, exclude=None):
        excl = exclude or ()
        for url in self._urls:
            if url not in excl:
                return url
        return None

    def report_done(self, url):
        self.done.append(url)

    def ready_replicas(self):
        return list(self._urls)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_replica(**attrs):
    handler = type("Replica", (_Replica,), dict(attrs))
    server = _Server(("127.0.0.1", 0), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def _start_lb(policy, **handler_attrs):
    handler_attrs.setdefault("journal_account", lb_lib.JournalAccount())
    handler = type("Handler", (lb_lib._ProxyHandler,), {
        "policy": policy, "recorder": lb_lib.RequestRecorder(),
        "breaker": lb_lib.CircuitBreaker(), **handler_attrs})
    server = lb_lib._ThreadingHTTPServer(("127.0.0.1", _free_port()),
                                         handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def _expected(prompt, mt):
    body = b"".join(f'data: {{"token": {_tok(prompt, p)}}}\n\n'.encode()
                    for p in range(mt))
    return body + b"data: [DONE]\n\n"


def _stream(base, doc, timeout=30):
    """POST a streaming /generate, reading until EOF. Returns
    (status, bytes, truncated) — truncated means the chunked stream
    died before its terminator (the LB gave up mid-stream)."""
    host, port = base.split("//", 1)[1].split(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        conn.request("POST", "/generate", body=json.dumps(doc),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        chunks, truncated = [], False
        try:
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                chunks.append(chunk)
        except (http.client.IncompleteRead, http.client.HTTPException,
                ConnectionError, OSError) as e:
            truncated = True
            partial = getattr(e, "partial", None)
            if partial:
                chunks.append(partial)
        return resp.status, b"".join(chunks), truncated
    finally:
        conn.close()


def _await(predicate, timeout=5.0):
    """The LB handler thread finishes its accounting (outcome
    counters, request-code labels, slot returns) a beat AFTER the
    client sees the stream terminator — poll instead of racing it."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


def _resumes(outcome):
    return lb_lib._RESUMES.labels(outcome=outcome).get()


def _code(code, method="POST"):
    return lb_lib._REQUESTS.labels(method=method, code=code).get()


def _gap_count():
    return lb_lib._RESUME_GAP.labels().snapshot()[2]


# ========================================================= unit layer
def test_sse_token_parse():
    assert lb_lib._sse_token(b'data: {"token": 42}\n\n') == 42
    assert lb_lib._sse_token(b"data: [DONE]\n\n") is None
    assert lb_lib._sse_token(b": keepalive\n\n") is None
    assert lb_lib._sse_token(b"data: not-json\n\n") is None
    assert lb_lib._sse_token(b'data: {"text": "hi"}\n\n') is None


def test_journal_account_charge_release():
    acct = lb_lib.JournalAccount(cap_bytes=100)
    assert acct.charge(60) and acct.used() == 60
    assert not acct.charge(41)      # over cap: refused, not clamped
    assert acct.used() == 60
    acct.release(60)
    assert acct.used() == 0
    acct.release(10)                # over-release clamps at zero
    assert acct.used() == 0


def test_stream_journal_resume_body_and_eviction():
    body = json.dumps({"prompt": [1, 2], "max_tokens": 8,
                       "stream": True, "seed": 7}).encode()
    doc = json.loads(body)
    acct = lb_lib.JournalAccount(cap_bytes=10 * 1024)
    j = lb_lib.StreamJournal({"path": "/generate", "body": body}, doc,
                             1, acct)
    assert j.can_resume() and acct.used() > 0
    # Before any token went out the re-submission IS the original
    # request (plain re-submit, nothing to dedupe).
    assert j.resume_body() == body
    j.append(10)
    j.append(11)
    resumed = json.loads(j.resume_body())
    assert resumed["resume"] == {"emitted": [10, 11], "pos": 2}
    assert resumed["seed"] == 7                   # original sampling
    j.release()
    assert acct.used() == 0

    # Cap too small for even the request body: evicted at birth, and
    # the account never leaks a partial charge.
    tiny = lb_lib.JournalAccount(cap_bytes=8)
    before = _resumes("evicted")
    j2 = lb_lib.StreamJournal({"path": "/generate", "body": body},
                              doc, 1, tiny)
    assert j2.evicted and not j2.can_resume()
    assert tiny.used() == 0
    assert _resumes("evicted") == before + 1
    j2.evict()                                    # idempotent
    assert _resumes("evicted") == before + 1


def test_maybe_journal_gates_on_streaming_generate_posts():
    def probe(method="POST", path="/generate", doc=None, body=None):
        if body is None:
            body = json.dumps(doc).encode() if doc is not None else b""
        ns = types.SimpleNamespace(max_stream_resumes=1, path=path,
                                   journal_account=None)
        return lb_lib._ProxyHandler._maybe_journal(
            ns, method, body, {"path": path, "body": body})

    ok = {"prompt": [1], "max_tokens": 4, "stream": True}
    assert isinstance(probe(doc=ok), lb_lib.StreamJournal)
    assert probe(path="/generate?x=1", doc=ok) is not None
    assert probe(method="GET", doc=ok) is None
    assert probe(path="/metrics", doc=ok) is None
    assert probe(body=b"not json") is None
    assert probe(body=b"") is None
    assert probe(doc={"prompt": [1]}) is None          # not streaming
    # A request that already carries `resume` belongs to an upstream
    # resuming tier — journaling it again would double-dedupe.
    assert probe(doc=dict(ok, resume={"emitted": [1],
                                      "pos": 1})) is None
    ns = types.SimpleNamespace(max_stream_resumes=0, path="/generate",
                               journal_account=None)
    body = json.dumps(ok).encode()
    assert lb_lib._ProxyHandler._maybe_journal(
        ns, "POST", body, {"path": "/generate", "body": body}) is None


# ================================================= LB splice behavior
def test_resume_splice_bit_identical_honored_peer():
    """Tentpole acceptance: upstream dies after 3 events, the LB
    splices the continuation from a resume-honoring peer — the client
    bytes equal the uninterrupted run byte for byte, the peer started
    at the absolute position (no replay), and the slot accounting
    returned every pick."""
    hits = []
    sa, a = _start_replica(abort_after=3, hits=hits)
    sb, b = _start_replica(hits=hits)
    policy = _OrderedPolicy([a, b])
    lb, base = _start_lb(policy)
    before_ok, before_gap = _resumes("ok"), _gap_count()
    before_200 = _code("200")
    try:
        prompt, mt = [3, 1, 4], 9
        status, body, truncated = _stream(
            base, {"prompt": prompt, "max_tokens": mt, "stream": True,
                   "seed": 5})
        assert status == 200 and not truncated
        assert body == _expected(prompt, mt)
        assert _await(lambda: _resumes("ok") == before_ok + 1)
        assert _gap_count() == before_gap + 1     # stall was measured
        assert _await(lambda: _code("200") == before_200 + 1)
        # Peer B was resumed AT position 3 (honored), not replayed.
        assert hits == [(sa.server_address[1], 0, False),
                        (sb.server_address[1], 3, True)]
        # Both the original pick and the resume pick returned slots.
        assert _await(lambda: sorted(policy.done) == sorted([a, b]))
    finally:
        lb.shutdown(), sa.shutdown(), sb.shutdown()


def test_resume_dedupes_replay_from_zero_peer():
    """A peer without resume admission replays from position 0: the
    LB drops the overlap (verifying each replayed token against its
    journal) and the client still sees one seamless stream."""
    hits = []
    sa, a = _start_replica(abort_after=4, hits=hits)
    sb, b = _start_replica(honor_resume=False, hits=hits)
    lb, base = _start_lb(_OrderedPolicy([a, b]))
    before_ok = _resumes("ok")
    try:
        prompt, mt = [2, 7], 10
        status, body, truncated = _stream(
            base, {"prompt": prompt, "max_tokens": mt, "stream": True})
        assert status == 200 and not truncated
        assert body == _expected(prompt, mt)
        assert _await(lambda: _resumes("ok") == before_ok + 1)
        assert hits[-1] == (sb.server_address[1], 0, False)  # replayed
    finally:
        lb.shutdown(), sa.shutdown(), sb.shutdown()


def test_resume_divergent_peer_aborts_instead_of_corrupting():
    """The replayed overlap is VERIFIED: a peer emitting different
    tokens (wrong weights, wrong seed path) must not be spliced — the
    client keeps a clean truncated stream ending at an event boundary,
    never silently wrong bytes."""
    sa, a = _start_replica(abort_after=3)
    sb, b = _start_replica(honor_resume=False, token_offset=5)
    lb, base = _start_lb(_OrderedPolicy([a, b]))
    before = {k: _resumes(k) for k in ("failed", "exhausted", "ok")}
    before_ua = _code("upstream_aborted")
    try:
        prompt, mt = [9, 9], 8
        status, body, truncated = _stream(
            base, {"prompt": prompt, "max_tokens": mt, "stream": True})
        assert status == 200 and truncated
        # Exactly the 3 pre-death events, all correct, no [DONE].
        want = b"".join(
            f'data: {{"token": {_tok(prompt, p)}}}\n\n'.encode()
            for p in range(3))
        assert body == want
        assert b"[DONE]" not in body
        assert _await(
            lambda: _resumes("failed") == before["failed"] + 1)
        assert _await(
            lambda: _resumes("exhausted") == before["exhausted"] + 1)
        assert _resumes("ok") == before["ok"]
        assert _await(
            lambda: _code("upstream_aborted") == before_ua + 1)
    finally:
        lb.shutdown(), sa.shutdown(), sb.shutdown()


def test_resume_budget_exhaustion_clean_abort():
    """Budget 1 (the default): when the continuation dies too, the
    stream degrades to a clean abort — every byte the client DID get
    is correct and ends at an event boundary."""
    sa, a = _start_replica(abort_after=3)
    sb, b = _start_replica(abort_after=2)      # continuation dies too
    lb, base = _start_lb(_OrderedPolicy([a, b]))
    before = {k: _resumes(k) for k in ("failed", "exhausted")}
    try:
        prompt, mt = [6, 2], 12
        status, body, truncated = _stream(
            base, {"prompt": prompt, "max_tokens": mt, "stream": True})
        assert status == 200 and truncated
        # 3 events from A + 2 spliced from B, all at the right
        # absolute positions.
        want = b"".join(
            f'data: {{"token": {_tok(prompt, p)}}}\n\n'.encode()
            for p in range(5))
        assert body == want
        assert _await(
            lambda: _resumes("failed") == before["failed"] + 1)
        assert _await(
            lambda: _resumes("exhausted") == before["exhausted"] + 1)
    finally:
        lb.shutdown(), sa.shutdown(), sb.shutdown()


def test_resume_budget_two_survives_double_death():
    """STPU_LB_STREAM_RESUMES=2 equivalent: two mid-stream deaths,
    two splices, one bit-identical client stream."""
    sa, a = _start_replica(abort_after=3)
    sb, b = _start_replica(abort_after=2)
    sc, c = _start_replica()
    lb, base = _start_lb(_OrderedPolicy([a, b, c]),
                         max_stream_resumes=2)
    before_ok = _resumes("ok")
    try:
        prompt, mt = [8, 8, 8], 11
        status, body, truncated = _stream(
            base, {"prompt": prompt, "max_tokens": mt, "stream": True,
                   "seed": 13})
        assert status == 200 and not truncated
        assert body == _expected(prompt, mt)
        assert _await(lambda: _resumes("ok") == before_ok + 1)
    finally:
        lb.shutdown(), sa.shutdown(), sb.shutdown(), sc.shutdown()


def test_resume_no_replica_left():
    """A single-replica service has nowhere to resume: the abort is
    clean and labeled no_replica, not a hang or a retry storm."""
    sa, a = _start_replica(abort_after=2)
    lb, base = _start_lb(_OrderedPolicy([a]))
    before = _resumes("no_replica")
    before_ua = _code("upstream_aborted")
    try:
        status, body, truncated = _stream(
            base, {"prompt": [1], "max_tokens": 6, "stream": True})
        assert status == 200 and truncated
        assert _await(lambda: _resumes("no_replica") == before + 1)
        assert _await(
            lambda: _code("upstream_aborted") == before_ua + 1)
    finally:
        lb.shutdown(), sa.shutdown()


def test_journal_cap_eviction_degrades_to_plain_abort():
    """STPU_LB_RESUME_JOURNAL_MB equivalent: a cap the stream outgrows
    evicts the journal mid-flight — the stream keeps proxying, the
    death degrades to a plain upstream abort, and every charged byte
    is released."""
    sa, a = _start_replica(abort_after=6)
    sb, b = _start_replica()
    # Body charge (+64) fits; the cap runs out after ~2 token appends,
    # well before the death at event 6.
    body = json.dumps({"prompt": [4, 4], "max_tokens": 10,
                       "stream": True}).encode()
    acct = lb_lib.JournalAccount(
        cap_bytes=len(body) + 64 + 2 * lb_lib.StreamJournal.TOKEN_BYTES)
    lb, base = _start_lb(_OrderedPolicy([a, b]), journal_account=acct)
    before_ev, before_ok = _resumes("evicted"), _resumes("ok")
    before_ua = _code("upstream_aborted")
    try:
        status, got, truncated = _stream(
            base, {"prompt": [4, 4], "max_tokens": 10, "stream": True})
        assert status == 200 and truncated
        assert _await(lambda: _resumes("evicted") == before_ev + 1)
        assert _await(
            lambda: _code("upstream_aborted") == before_ua + 1)
        assert _resumes("ok") == before_ok          # no resume attempt
        assert _await(lambda: acct.used() == 0)     # nothing leaked
    finally:
        lb.shutdown(), sa.shutdown(), sb.shutdown()


def test_journal_released_after_clean_completion():
    acct = lb_lib.JournalAccount()
    sa, a = _start_replica()
    lb, base = _start_lb(_OrderedPolicy([a]), journal_account=acct)
    try:
        prompt, mt = [5], 7
        status, body, truncated = _stream(
            base, {"prompt": prompt, "max_tokens": mt, "stream": True})
        assert status == 200 and not truncated
        assert body == _expected(prompt, mt)
        assert _await(lambda: acct.used() == 0)
    finally:
        lb.shutdown(), sa.shutdown()


def test_client_disconnect_is_not_resumed_and_not_charged():
    """Satellite (a): the CLIENT hanging up mid-stream is not an
    upstream failure — no resume attempt, no breaker charge, and the
    request lands under code="client_closed" (which the SLO burn
    monitor does not count as bad)."""
    sa, a = _start_replica(delay=0.02)
    sb, b = _start_replica(delay=0.02)
    lb, base = _start_lb(_OrderedPolicy([a, b]))
    resumes_before = {k: _resumes(k)
                      for k in ("ok", "failed", "no_replica")}
    cc_before, ua_before = _code("client_closed"), _code(
        "upstream_aborted")
    try:
        host, port = base.split("//", 1)[1].split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.request("POST", "/generate",
                     body=json.dumps({"prompt": [1, 2],
                                      "max_tokens": 50,
                                      "stream": True}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read1(1)                      # stream demonstrably live
        # A REAL client death: SO_LINGER(0) close sends RST so the
        # LB's next write fails (a plain close() here would leave the
        # fd alive via the response object's makefile reference).
        conn.sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
        resp.close()
        conn.close()                       # client dies mid-stream
        assert _await(lambda: _code("client_closed") == cc_before + 1,
                      timeout=10)
        assert _code("upstream_aborted") == ua_before
        for k, v in resumes_before.items():
            assert _resumes(k) == v, f"resume outcome {k} moved"
        # No breaker charge for a client hang-up: both replicas stay
        # selectable.
        handler = lb.RequestHandlerClass
        assert handler.breaker.blocked([a, b]) == set()
    finally:
        lb.shutdown(), sa.shutdown(), sb.shutdown()


def test_lb_stream_fault_point_heals_via_resume():
    """Satellite (b): the game-day lever. ``lb.stream`` killing the
    proxied stream after K upstream reads is healed by the resume
    ladder — the client never notices the drill."""
    sa, a = _start_replica(delay=0.005)
    sb, b = _start_replica(delay=0.005)
    lb, base = _start_lb(_OrderedPolicy([a, b]))
    before_ok = _resumes("ok")
    try:
        fi.activate("lb.stream", times=1, skip=3)
        prompt, mt = [7, 7], 10
        status, body, truncated = _stream(
            base, {"prompt": prompt, "max_tokens": mt, "stream": True})
        assert fi.fires("lb.stream") == 1
        assert status == 200 and not truncated
        assert body == _expected(prompt, mt)
        assert _await(lambda: _resumes("ok") == before_ok + 1)
    finally:
        fi.clear()
        lb.shutdown(), sa.shutdown(), sb.shutdown()


# ============================================ engine resume admission
def _tiny_llm():
    import jax
    from skypilot_tpu.models import llama
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    return cfg, params


def _post_json(base, doc, timeout=120):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _sse_tokens(body):
    return [json.loads(ln[6:])["token"]
            for ln in body.decode().splitlines()
            if ln.startswith("data: {")]


def test_replica_resume_admission_bit_identical():
    """Engine resume admission end to end on a real replica: the
    emitted prefix re-enters as a prompt extension and the
    continuation equals the uninterrupted run's tail exactly — greedy
    AND seeded — with X-STPU-Resume acknowledging the admission on
    the stream path. Malformed resumes keep the 400 contract."""
    from skypilot_tpu.recipes import serve_llm

    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=120)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    prompt, mt, cut = [1, 2, 3], 8, 3
    try:
        for sampling in ({"temperature": 0.0},
                         {"temperature": 0.9, "seed": 7}):
            status, _, raw = _post_json(
                base, {"prompt": prompt, "max_tokens": mt, **sampling})
            assert status == 200
            full = json.loads(raw)["tokens"]
            assert len(full) == mt

            resume = {"emitted": full[:cut], "pos": cut}
            # Non-stream continuation: exactly the tail.
            status, _, raw = _post_json(
                base, {"prompt": prompt, "max_tokens": mt,
                       "resume": resume, **sampling})
            assert status == 200
            assert json.loads(raw)["tokens"] == full[cut:]
            # Stream continuation: acknowledged + bit-identical tail.
            status, headers, raw = _post_json(
                base, {"prompt": prompt, "max_tokens": mt,
                       "stream": True, "resume": resume, **sampling})
            assert status == 200
            assert headers.get("X-STPU-Resume") == str(cut)
            assert _sse_tokens(raw) == full[cut:]
            assert raw.rstrip().endswith(b"data: [DONE]")

        # 400 contract: malformed resumes are refused BEFORE any
        # engine admission.
        for bad in ([1, 2],                          # not an object
                    {"emitted": [], "pos": 0},       # empty
                    {"emitted": [1, 2], "pos": 3},   # pos mismatch
                    {"emitted": list(range(mt)), "pos": mt}):  # >= mt
            status, _, raw = _post_json(
                base, {"prompt": prompt, "max_tokens": mt,
                       "resume": bad})
            assert status == 400, (bad, raw)
    finally:
        httpd.engine.shutdown()
        httpd.shutdown()


def test_replica_resume_requires_engine():
    """The legacy locked path has no absolute-position sampling
    contract: resume against engine_slots=0 is a clean 400, not a
    silently-wrong continuation."""
    from skypilot_tpu.recipes import serve_llm

    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=120)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        status, _, raw = _post_json(
            base, {"prompt": [1, 2], "max_tokens": 6,
                   "resume": {"emitted": [5], "pos": 1}})
        assert status == 400
        assert b"engine" in raw
    finally:
        httpd.shutdown()


def test_engine_resume_paged_spec_quant_bit_identical():
    """Engine-level resume admission with the hard config on: paged
    KV + int8 KV quant + speculative decoding. submit(resume=prefix)
    must continue at the same absolute positions — greedy and
    seeded — because resumed sampling keys are fold_in(seed, pos),
    not a function of what lives in this replica's cache."""
    from skypilot_tpu.serve import decode_engine

    cfg, params = _tiny_llm()
    engine = decode_engine.DecodeEngine(
        cfg, params, slots=2, max_seq=128, prefill_chunk=8,
        paged=True, kv_quant=True, spec_k=3, spec_ngram=2,
        use_manifest=False).start()
    prompt, mt, cut = [1, 2, 3, 4], 10, 4
    try:
        for temperature, seed in ((0.0, 0), (0.8, 11)):
            full = engine.submit(prompt, max_tokens=mt,
                                 temperature=temperature,
                                 seed=seed).result(timeout=300)
            assert len(full) == mt
            tail = engine.submit(prompt, max_tokens=mt - cut,
                                 temperature=temperature, seed=seed,
                                 resume=full[:cut]).result(timeout=300)
            assert tail == full[cut:], (temperature, seed)
    finally:
        engine.shutdown()


# =========================================== e2e: kill a real replica
def test_e2e_mid_stream_replica_death_bit_identical():
    """The whole ladder on real replicas: two engine-backed serve_llm
    servers behind the LB; the stream's upstream dies mid-flight
    (injected stream kill for greedy + seeded, then a REAL engine
    death) and the client's bytes equal the uninterrupted reference
    every time. Token determinism across replicas is the engine's
    replica-independent fold_in(seed, position) sampling."""
    from skypilot_tpu.recipes import serve_llm

    cfg, params = _tiny_llm()
    servers = []
    for _ in range(2):
        ready = threading.Event()
        httpd = serve_llm.serve(cfg, params, 0, ready_event=ready)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        assert ready.wait(timeout=120)
        servers.append(httpd)
    sa, sb = servers
    a = f"http://127.0.0.1:{sa.server_address[1]}"
    b = f"http://127.0.0.1:{sb.server_address[1]}"
    # breaker=None: the injected kills below must not eject replica A
    # from selection — each round has to START on A to die there.
    lb, base = _start_lb(_OrderedPolicy([a, b]), breaker=None,
                         upstream_timeout=120)
    prompt, mt = [1, 2, 3], 12
    greedy = {"prompt": prompt, "max_tokens": mt, "stream": True}
    seeded = dict(greedy, temperature=0.9, seed=21)
    try:
        # Uninterrupted references, straight from replica B.
        refs = {}
        for name, doc in (("greedy", greedy), ("seeded", seeded)):
            status, body, truncated = _stream(b, doc, timeout=120)
            assert status == 200 and not truncated
            refs[name] = body

        # Injected stream kill (fault point lb.stream), both sampling
        # modes: the resume splice from B is bit-identical.
        for name, doc in (("greedy", greedy), ("seeded", seeded)):
            before_ok = _resumes("ok")
            fi.activate("lb.stream", times=1, skip=4)
            try:
                status, body, truncated = _stream(base, doc,
                                                  timeout=120)
            finally:
                fi.clear()
            assert status == 200 and not truncated, name
            assert body == refs[name], f"{name} splice diverged"
            assert _await(lambda: _resumes("ok") == before_ok + 1)

        # A REAL replica death: slow the decode so the kill lands
        # mid-stream, then shut A's engine down under a live stream.
        fi.activate("engine.step", mode="delay", delay=0.03)
        before_ok = _resumes("ok")
        result = {}

        def consume():
            result["out"] = _stream(base, seeded, timeout=120)

        client = threading.Thread(target=consume, daemon=True)
        client.start()
        deadline = time.time() + 60
        while time.time() < deadline:       # wait: stream in flight
            if sa.engine.in_flight() >= 1:
                break
            time.sleep(0.01)
        else:
            pytest.fail("stream never reached replica A")
        time.sleep(0.1)                     # a few tokens out first
        sa.engine.shutdown()                # the preempted replica
        client.join(timeout=120)
        fi.clear()
        assert "out" in result, "client stream never finished"
        status, body, truncated = result["out"]
        assert status == 200 and not truncated
        assert body == refs["seeded"], "post-death splice diverged"
        assert _await(lambda: _resumes("ok") == before_ok + 1)
    finally:
        fi.clear()
        lb.shutdown()
        for httpd in servers:
            try:
                httpd.engine.shutdown()
            except Exception:   # noqa: BLE001 — A's engine already dead
                pass
            httpd.shutdown()
