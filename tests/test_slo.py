"""SLO burn-rate monitor (observability/slo.py).

The contract under test: Objective config validation, the Google-SRE
burn definition (bad_fraction / (1 - target)) over fast + slow
windows, breach = BOTH windows over the threshold with edge-triggered
slo_breach / slo_recovered events, and the satellite-3 guarantee that
an empty or all-zero window yields burn None — never NaN, which would
compare False against the threshold and read as healthy mid-outage.
"""
import pytest

from skypilot_tpu.observability import events
from skypilot_tpu.observability import slo
from skypilot_tpu.observability.promtext import HistogramSnapshot
from skypilot_tpu.observability.timeseries import TimeSeriesStore


def _snap(counts, bounds=(0.1, 1.0)):
    cum, total = [], 0.0
    for c in counts:
        total += c
        cum.append(total)
    return HistogramSnapshot(bounds=list(bounds), cumulative=cum,
                             sum=float(total), count=total)


def _store():
    return TimeSeriesStore(raw_seconds=1.0, raw_retention=10000.0)


def _monitor(store, kind="ttft", target=0.9, threshold_s=1.0,
             **kw):
    config = {"kind": kind, "target": target}
    if threshold_s is not None:
        config["threshold_seconds"] = threshold_s
    return slo.SloMonitor(
        "svc", [slo.Objective.from_config(config)], store,
        fast_window=10.0, slow_window=100.0, **kw)


# ----------------------------------------------------------- objectives
def test_objective_config_validation():
    obj = slo.Objective.from_config(
        {"kind": "ttft", "target": 0.99, "threshold_seconds": 0.5})
    assert obj.to_config() == {"kind": "ttft", "target": 0.99,
                               "threshold_seconds": 0.5}
    # error_rate: no threshold; default target applies.
    obj = slo.Objective.from_config({"kind": "error_rate"})
    assert obj.target == 0.99 and obj.threshold_s is None
    with pytest.raises(ValueError, match="kind"):
        slo.Objective.from_config({"kind": "latency"})
    with pytest.raises(ValueError, match="target"):
        slo.Objective.from_config(
            {"kind": "ttft", "target": 1.0, "threshold_seconds": 1})
    with pytest.raises(ValueError, match="threshold_seconds"):
        slo.Objective.from_config({"kind": "tpot"})
    with pytest.raises(ValueError, match="threshold_seconds"):
        slo.Objective.from_config(
            {"kind": "ttft", "threshold_seconds": 0})
    with pytest.raises(ValueError, match="no threshold"):
        slo.Objective.from_config(
            {"kind": "error_rate", "threshold_seconds": 1})


def test_from_spec_returns_none_without_objectives():
    class Spec:
        slo_objectives = None
    assert slo.SloMonitor.from_spec("svc", Spec(), _store()) is None


# ------------------------------------------------------------ burn math
def test_burn_rate_latency_objective():
    """10% of requests over a 1.0s threshold against a 0.9 target:
    bad_fraction == 1 - target, so burn == 1.0 in both windows."""
    store = _store()
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([0, 0, 0]), ts=0.0)
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([90, 0, 10]), ts=5.0)
    monitor = _monitor(store, target=0.9, threshold_s=1.0)
    state = monitor.evaluate(now=5.0)
    entry = state["objectives"][0]
    assert entry["burn_fast"] == pytest.approx(1.0)
    assert entry["burn_slow"] == pytest.approx(1.0)
    assert entry["budget_remaining"] == pytest.approx(0.0)


def test_threshold_resolves_to_enclosing_bucket():
    """A threshold between bounds counts the cumulative total at the
    first bound >= threshold (documented bucket resolution)."""
    store = _store()
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([0, 0, 0]), ts=0.0)
    # 50 at <=0.1, 50 in (0.1, 1.0]; threshold 0.5 resolves to the
    # 1.0 bound, so all 100 are good.
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([50, 50, 0]), ts=5.0)
    monitor = _monitor(store, target=0.9, threshold_s=0.5)
    entry = monitor.evaluate(now=5.0)["objectives"][0]
    assert entry["burn_fast"] == pytest.approx(0.0)


def test_tpot_objective_reads_decode_phase_only():
    store = _store()
    for phase, counts in (("decode", [0, 0, 0]),
                          ("prefill", [0, 0, 0])):
        store.record_histogram("stpu_engine_step_seconds",
                               _snap(counts), ts=0.0, phase=phase)
    # Decode clean, prefill awful: only decode may count.
    store.record_histogram("stpu_engine_step_seconds",
                           _snap([100, 0, 0]), ts=5.0, phase="decode")
    store.record_histogram("stpu_engine_step_seconds",
                           _snap([0, 0, 100]), ts=5.0, phase="prefill")
    monitor = _monitor(store, kind="tpot", target=0.9, threshold_s=1.0)
    entry = monitor.evaluate(now=5.0)["objectives"][0]
    assert entry["burn_fast"] == pytest.approx(0.0)


def test_error_rate_objective_counts_5xx_zero_and_aborted():
    store = _store()
    for code, t0, t1 in (("200", 0.0, 86.0), ("500", 0.0, 5.0),
                         ("aborted", 0.0, 1.0), ("0", 0.0, 2.0),
                         ("upstream_aborted", 0.0, 2.0),
                         ("client_closed", 0.0, 4.0),
                         ("404", 0.0, 10.0)):
        store.record("stpu_lb_requests_total", t0, ts=0.0, code=code)
        store.record("stpu_lb_requests_total", t1, ts=5.0, code=code)
    monitor = _monitor(store, kind="error_rate", target=0.9,
                       threshold_s=None)
    entry = monitor.evaluate(now=5.0)["objectives"][0]
    # bad = 5 + 1 + 2 + 2 of 110 total: 5xx, the legacy "aborted",
    # "0", and "upstream_aborted" burn budget; a 404 is a client
    # error and "client_closed" is the client hanging up — neither
    # is the service's failure.
    assert entry["burn_fast"] == pytest.approx((10 / 110) / 0.1)


# --------------------------------------------- satellite 3: None not NaN
def test_empty_window_yields_none_never_nan():
    store = _store()
    monitor = _monitor(store)
    state = monitor.evaluate(now=5.0)
    entry = state["objectives"][0]
    assert entry["burn_fast"] is None
    assert entry["burn_slow"] is None
    assert entry["budget_remaining"] is None
    assert entry["breaching"] is False
    assert state["degraded"] is False


def test_all_zero_window_yields_none_never_nan():
    """Traffic stopped: the histogram delta over the window has
    count == 0 (quantile math would be NaN). Burn must be None."""
    store = _store()
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([50, 0, 0]), ts=0.0)
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([50, 0, 0]), ts=100.0)
    monitor = _monitor(store)
    entry = monitor.evaluate(now=100.0)["objectives"][0]
    assert entry["burn_fast"] is None       # fast window: no new obs
    assert entry["breaching"] is False


# -------------------------------------------------- breach edges + events
def test_breach_needs_both_windows_and_emits_edge_events(tmp_state_dir):
    store = _store()
    monitor = _monitor(store, target=0.9, threshold_s=1.0)

    def feed(ts, good, bad):
        store.record_histogram("stpu_lb_ttfb_seconds",
                               _snap([good, 0, bad]), ts=ts)

    feed(0.0, 0, 0)
    feed(5.0, 0, 100)                       # both windows burning
    state = monitor.evaluate(now=5.0)
    assert state["objectives"][0]["breaching"] is True
    assert state["degraded"] is True
    assert monitor.degraded() is True
    recs = events.read(kind="slo", name="svc")
    assert [r["event"] for r in recs] == ["slo_breach"]
    assert recs[-1]["objective"] == "ttft"
    assert recs[-1]["burn_fast"] >= 1.0

    # Still breaching: NO duplicate event (edge-triggered).
    monitor.evaluate(now=6.0)
    assert len(events.read(kind="slo", name="svc")) == 1

    # Recovery: fast window goes clean (slow still remembers the bad
    # spell) — breach needs BOTH, so this recovers and emits the edge.
    feed(50.0, 1000, 0)
    state = monitor.evaluate(now=50.0)
    assert state["objectives"][0]["breaching"] is False
    recs = events.read(kind="slo", name="svc")
    assert [r["event"] for r in recs] == ["slo_breach", "slo_recovered"]
    assert monitor.degraded() is False


def test_latency_signals_seam():
    store = _store()
    monitor = _monitor(store, target=0.9, threshold_s=1.0)
    # Before any evaluation: empty signals, not a crash.
    assert monitor.latency_signals() == {"degraded": False}
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([0, 0, 0]), ts=0.0)
    store.record_histogram("stpu_lb_ttfb_seconds",
                           _snap([0, 0, 100]), ts=5.0)
    monitor.evaluate(now=5.0)
    signals = monitor.latency_signals()
    assert signals["degraded"] is True
    assert signals["ttft"]["breaching"] is True
    assert signals["ttft"]["burn_fast"] == pytest.approx(10.0)
    assert signals["ttft"]["burn_slow"] == pytest.approx(10.0)
