"""End-to-end launch/exec/queue/cancel/teardown on the local provider.

The hermetic multi-host harness SURVEY.md §4 calls for: each "host" is a
directory + subprocess, so gang execution, the env contract, job state,
logs, and teardown are exercised for real — no cloud, no TPU.
"""
import json
import pathlib
import time

import pytest

from skypilot_tpu import core, execution, exceptions, global_user_state
from skypilot_tpu.agent import job_lib
from skypilot_tpu.resources import Resources
from skypilot_tpu.status_lib import ClusterStatus
from skypilot_tpu.task import Task


def _local_res(hosts_per_slice=1):
    return Resources(cloud="local",
                     labels={"hosts_per_slice": str(hosts_per_slice)})


def _wait_job(handle, job_id, timeout=30):
    from skypilot_tpu.backends import slice_backend
    backend = slice_backend.SliceBackend()
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = backend.job_status(handle, job_id)
        if st and job_lib.JobStatus(st).is_terminal():
            return st
        time.sleep(0.2)
    raise TimeoutError(f"job {job_id} still {st}")


@pytest.mark.usefixtures("tmp_state_dir")
def test_launch_end_to_end_env_contract():
    """2 slices x 2 hosts: every host sees the full rank/env contract."""
    task = Task("envcheck", run=(
        'echo "rank=$SKYPILOT_NODE_RANK nodes=$SKYPILOT_NUM_NODES '
        'slice=$SKYPILOT_SLICE_INDEX coord=$SKYPILOT_COORDINATOR_ADDR" '
        '> ~/env_out.txt'), num_nodes=2)
    task.set_resources(_local_res(hosts_per_slice=2))
    job_id, handle = execution.launch(task, cluster_name="t-env",
                                      detach_run=True, stream_logs=False)
    assert job_id == 1
    status = _wait_job(handle, job_id)
    assert status == "SUCCEEDED"

    # Check each host's env file: ranks 0..3, slice = rank // 2.
    insts = handle.cluster_info.ordered_instances()
    assert len(insts) == 4
    for rank, inst in enumerate(insts):
        content = open(inst.tags["host_dir"] + "/env_out.txt").read()
        assert f"rank={rank} " in content
        assert "nodes=4" in content
        assert f"slice={rank // 2}" in content
        assert ":8476" in content

    record = global_user_state.get_cluster_from_name("t-env")
    assert record["status"] == ClusterStatus.UP


@pytest.mark.usefixtures("tmp_state_dir")
def test_gang_failure_cancels_all_hosts():
    """One host failing must take down the gang (rc-137 semantics)."""
    task = Task("gangfail", run=(
        'if [ "$SKYPILOT_NODE_RANK" = "1" ]; then exit 3; fi; '
        'sleep 60'), num_nodes=3)
    task.set_resources(_local_res())
    t0 = time.time()
    job_id, handle = execution.launch(task, cluster_name="t-gang",
                                      detach_run=True, stream_logs=False)
    status = _wait_job(handle, job_id, timeout=30)
    assert status == "FAILED"
    # Far faster than the 60s sleep: survivors were force-cancelled.
    assert time.time() - t0 < 30
    # The cancelled node's log is annotated with the gang rc. Logs are
    # head-resident: the head's job DB records where they landed.
    import pathlib

    from skypilot_tpu import core as core_lib
    job = {j["job_id"]: j for j in core_lib.queue("t-gang")}[job_id]
    log_dir = pathlib.Path(job["log_dir"])
    combined = "".join(
        p.read_text() for p in log_dir.glob("node-*.log"))
    assert "rc=137" in combined


@pytest.mark.usefixtures("tmp_state_dir")
def test_exec_reuse_queue_cancel_and_logs(capfd):
    task = Task("first", run="echo hello-from-run", num_nodes=1)
    task.set_resources(_local_res())
    job_id, handle = execution.launch(task, cluster_name="t-reuse",
                                      detach_run=True, stream_logs=False)
    assert _wait_job(handle, job_id) == "SUCCEEDED"

    # exec on the same cluster: no re-provision; job id increments.
    task2 = Task("second", run="sleep 30")
    task2.set_resources(_local_res())
    job_id2, _ = execution.exec(task2, "t-reuse", detach_run=True,
                                stream_logs=False)
    assert job_id2 == 2

    jobs = core.queue("t-reuse")
    assert [j["job_id"] for j in jobs] == [2, 1]

    cancelled = core.cancel("t-reuse", job_ids=[job_id2])
    assert cancelled == [job_id2]
    st = core.job_status("t-reuse", [job_id2])[job_id2]
    assert st == "CANCELLED"

    # tail_logs of the finished first job prints its output (streamed
    # from the head-side job_cli subprocess, so capture at fd level).
    rc = core.tail_logs("t-reuse", job_id, follow=False)
    out = capfd.readouterr().out
    assert "hello-from-run" in out
    assert rc == 0


@pytest.mark.usefixtures("tmp_state_dir")
def test_exec_on_missing_or_mismatched_cluster():
    task = Task("t", run="true")
    task.set_resources(_local_res())
    with pytest.raises(exceptions.ClusterNotUpError):
        execution.exec(task, "nope", stream_logs=False)


@pytest.mark.usefixtures("tmp_state_dir")
def test_workdir_and_setup(tmp_path):
    wd = tmp_path / "proj"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-42")
    task = Task("wd", workdir=str(wd),
                setup="cp ~/stpu_workdir/data.txt ~/setup_saw_it.txt",
                run="cat data.txt > ~/run_saw_it.txt", num_nodes=2)
    task.set_resources(_local_res())
    job_id, handle = execution.launch(task, cluster_name="t-wd",
                                      detach_run=True, stream_logs=False)
    assert _wait_job(handle, job_id) == "SUCCEEDED"
    for inst in handle.cluster_info.ordered_instances():
        host = inst.tags["host_dir"]
        assert open(host + "/setup_saw_it.txt").read() == "payload-42"
        assert open(host + "/run_saw_it.txt").read() == "payload-42"


@pytest.mark.usefixtures("tmp_state_dir")
def test_stop_down_and_cost_report():
    task = Task("life", run="true")
    task.set_resources(_local_res())
    job_id, handle = execution.launch(task, cluster_name="t-life",
                                      detach_run=True, stream_logs=False)
    _wait_job(handle, job_id)

    core.stop("t-life")
    record = global_user_state.get_cluster_from_name("t-life")
    assert record["status"] == ClusterStatus.STOPPED

    # status(refresh=True) agrees with provider truth.
    records = core.status(refresh=True)
    assert records[0]["status"] == ClusterStatus.STOPPED

    core.down("t-life")
    assert global_user_state.get_cluster_from_name("t-life") is None

    report = core.cost_report()
    names = [r["name"] for r in report]
    assert "t-life (terminated)" in names


@pytest.mark.usefixtures("tmp_state_dir")
def test_autostop_roundtrip():
    task = Task("auto", run="true")
    task.set_resources(_local_res())
    _, handle = execution.launch(task, cluster_name="t-auto",
                                 detach_run=True, stream_logs=False,
                                 idle_minutes_to_autostop=5)
    record = global_user_state.get_cluster_from_name("t-auto")
    assert record["autostop"] == 5
    core.autostop("t-auto", 10, down_after=True)
    record = global_user_state.get_cluster_from_name("t-auto")
    assert record["autostop"] == 10 and record["to_down"]


@pytest.mark.usefixtures("tmp_state_dir")
def test_tpu_pod_cannot_stop():
    """Multi-host slices are terminate-only (mirrors TPU VM semantics)."""
    from skypilot_tpu.backends import slice_backend
    task = Task("podstop", run="true")
    task.set_resources(_local_res())
    _, handle = execution.launch(task, cluster_name="t-pod",
                                 detach_run=True, stream_logs=False)
    # Fake a pod-sized launched resource on the handle.
    handle.launched_resources = Resources(accelerator="tpu-v5p-64")
    backend = slice_backend.SliceBackend()
    with pytest.raises(exceptions.NotSupportedError, match="terminate"):
        backend.teardown(handle, terminate=False)


@pytest.mark.usefixtures("tmp_state_dir")
def test_stop_start_cycle_resets_autostop():
    """stop -> start: the cluster comes back UP, runs jobs again, and a
    previous autostop setting is cleared in the DB and in the on-host
    autostop.json (reference `sky start` semantics). Enforcement by the
    daemon itself is covered in test_daemon_autostop.py; the daemon is
    disabled under this suite's fixture."""
    task = Task("cycle", run="echo first-run")
    task.set_resources(_local_res())
    job_id, handle = execution.launch(task, cluster_name="t-cycle",
                                      detach_run=True, stream_logs=False,
                                      idle_minutes_to_autostop=1)
    assert _wait_job(handle, job_id) == "SUCCEEDED"
    record = global_user_state.get_cluster_from_name("t-cycle")
    assert record["autostop"] == 1

    core.stop("t-cycle")
    assert global_user_state.get_cluster_from_name(
        "t-cycle")["status"] == ClusterStatus.STOPPED

    handle = core.start("t-cycle")
    record = global_user_state.get_cluster_from_name("t-cycle")
    assert record["status"] == ClusterStatus.UP
    # Autostop disabled by the restart, in the DB and on the host.
    assert record["autostop"] == -1
    cfg = json.loads(
        (pathlib.Path(handle.head_home) / ".stpu_agent" /
         "autostop.json").read_text())
    assert cfg["idle_minutes"] == -1

    # The restarted cluster executes jobs again.
    task2 = Task("after-restart", run="echo second-run")
    task2.set_resources(_local_res())
    job_id2, _ = execution.exec(task2, "t-cycle", detach_run=True,
                                stream_logs=False)
    assert _wait_job(handle, job_id2) == "SUCCEEDED"
    core.down("t-cycle")


@pytest.mark.usefixtures("tmp_state_dir")
def test_launch_with_ports_opens_and_cleans_up(monkeypatch):
    """resources.ports drives the provision SPI's open_ports at launch
    and cleanup_ports at terminate (VERDICT r4 next #1 done-bar). Spied
    at the SPI routing layer so the full backend path is exercised."""
    from skypilot_tpu import provision as provision_api
    from skypilot_tpu.backends import slice_backend
    events = []
    real_open, real_cleanup = (provision_api.open_ports,
                               provision_api.cleanup_ports)
    monkeypatch.setattr(
        slice_backend.provision_api, "open_ports",
        lambda prov, name, ports, cfg:
            (events.append(("open", prov, name, tuple(ports))),
             real_open(prov, name, ports, cfg))[1])
    monkeypatch.setattr(
        slice_backend.provision_api, "cleanup_ports",
        lambda prov, name, ports, cfg:
            (events.append(("cleanup", prov, name, tuple(ports))),
             real_cleanup(prov, name, ports, cfg))[1])

    task = Task("portful", run="true")
    task.set_resources(Resources(cloud="local", ports=("8080",)))
    _, handle = execution.launch(task, cluster_name="t-ports",
                                 detach_run=True, stream_logs=False)
    assert ("open", "local", "t-ports", ("8080",)) in events
    backend = slice_backend.SliceBackend()
    backend.teardown(handle, terminate=True)
    assert ("cleanup", "local", "t-ports", ("8080",)) in events


@pytest.mark.usefixtures("tmp_state_dir")
def test_launch_without_ports_skips_port_ops(monkeypatch):
    from skypilot_tpu.backends import slice_backend
    called = []
    monkeypatch.setattr(
        slice_backend.provision_api, "open_ports",
        lambda *a, **k: called.append(a))
    task = Task("portless", run="true")
    task.set_resources(Resources(cloud="local"))
    _, handle = execution.launch(task, cluster_name="t-noports",
                                 detach_run=True, stream_logs=False)
    assert called == []
    slice_backend.SliceBackend().teardown(handle, terminate=True)


@pytest.mark.usefixtures("tmp_state_dir")
def test_reused_cluster_opens_new_ports(monkeypatch):
    """`launch -c existing` with new ports must open them (fresh-
    provision open_ports is skipped on reuse) and persist the union so
    a later teardown cleans them."""
    from skypilot_tpu.backends import slice_backend
    events = []
    monkeypatch.setattr(
        slice_backend.provision_api, "open_ports",
        lambda prov, name, ports, cfg: events.append(
            ("open", name, tuple(ports))))
    monkeypatch.setattr(
        slice_backend.provision_api, "cleanup_ports",
        lambda prov, name, ports, cfg: events.append(
            ("cleanup", name, tuple(ports))))

    task = Task("first", run="true")
    task.set_resources(Resources(cloud="local"))
    _, handle = execution.launch(task, cluster_name="t-reup",
                                 detach_run=True, stream_logs=False)
    assert events == []  # portless launch: no port ops

    task2 = Task("second", run="true")
    task2.set_resources(Resources(cloud="local", ports=("8080",)))
    _, handle = execution.launch(task2, cluster_name="t-reup",
                                 detach_run=True, stream_logs=False)
    assert ("open", "t-reup", ("8080",)) in events
    # Union persisted: teardown cleans the rule even though the FIRST
    # launch had no ports.
    record = global_user_state.get_cluster_from_name("t-reup")
    assert record["handle"].launched_resources.ports == ("8080",)
    slice_backend.SliceBackend().teardown(record["handle"],
                                          terminate=True)
    assert ("cleanup", "t-reup", ("8080",)) in events


@pytest.mark.usefixtures("tmp_state_dir")
def test_status_endpoints_flag(monkeypatch):
    """`stpu status --endpoints` maps a cluster's opened ports to
    reachable endpoints through the provision SPI's query_ports."""
    from click.testing import CliRunner

    from skypilot_tpu import cli as cli_mod
    task = Task("portful", run="true")
    task.set_resources(Resources(cloud="local", ports=("8080",)))
    execution.launch(task, cluster_name="t-eps", detach_run=True,
                     stream_logs=False)
    result = CliRunner().invoke(cli_mod.cli, ["status", "--endpoints",
                                              "t-eps"])
    assert result.exit_code == 0, result.output
    assert "8080 -> http://" in result.output
    from skypilot_tpu.backends import slice_backend
    record = global_user_state.get_cluster_from_name("t-eps")
    slice_backend.SliceBackend().teardown(record["handle"],
                                          terminate=True)
