"""MoE routing + expert-parallel Mixtral tests (8-device CPU mesh)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import mixtral
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer


def test_top2_dispatch_properties():
    gates = jax.nn.softmax(
        jax.random.normal(jax.random.key(0), (64, 4)), axis=-1)
    dispatch, combine, aux = mixtral._top2_dispatch(gates, capacity=40)
    # each token dispatched to <= 2 experts, combine weights sum to ~1
    per_token = jnp.sum(dispatch, axis=(1, 2))
    assert int(jnp.max(per_token)) <= 2
    sums = jnp.sum(combine, axis=(1, 2))
    kept = per_token == 2
    np.testing.assert_allclose(np.asarray(sums[kept]), 1.0, rtol=1e-5)
    # no slot is used twice within an expert
    slot_usage = jnp.sum(dispatch, axis=0)  # (E, C)
    assert int(jnp.max(slot_usage)) <= 1
    assert float(aux) > 0


def test_capacity_drops_overflow_tokens():
    # All tokens prefer expert 0 -> capacity clips most of them.
    gates = jnp.tile(jnp.array([[0.9, 0.1, 0.0, 0.0]]), (32, 1))
    dispatch, combine, _ = mixtral._top2_dispatch(gates, capacity=4)
    assert int(jnp.sum(dispatch[:, 0])) == 4  # expert 0 full
    assert int(jnp.sum(dispatch[:, 1])) == 4  # expert 1 full (top-2)


def test_mixtral_forward_and_train_ep():
    cfg = mixtral.MixtralConfig.tiny(vocab_size=64)
    mesh = mesh_lib.make_mesh({"dp": 2, "ep": 4})
    params = mixtral.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(
        learning_rate=5e-3, warmup_steps=1, total_steps=30))
    state = trainer.init_train_state(params, tx)
    shardings = trainer.state_shardings(
        mesh, mesh_lib.DEFAULT_RULES, mixtral.param_specs(cfg),
        jax.eval_shape(lambda: state))
    state = jax.device_put(state, shardings)
    # experts actually sharded over ep
    assert state.params["layers"]["w_gate"].sharding.spec[1] == "ep"

    def fwd(p, t, constrain):
        return mixtral.forward(cfg, p, t, constrain=constrain,
                               with_aux=True)

    step = trainer.make_train_step(fwd, tx, mesh, mesh_lib.DEFAULT_RULES)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, 64)
    state, m0 = step(state, {"tokens": tokens})
    assert float(m0["aux_loss"]) > 0  # router aux loss flows into training
    for _ in range(10):
        state, m = step(state, {"tokens": tokens})
    assert float(m["loss"]) < float(m0["loss"])
