"""MoE routing + expert-parallel Mixtral tests (8-device CPU mesh)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import mixtral
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer


def test_top2_dispatch_properties():
    gates = jax.nn.softmax(
        jax.random.normal(jax.random.key(0), (64, 4)), axis=-1)
    dispatch, combine, aux = mixtral._top2_dispatch(gates, capacity=40)
    # each token dispatched to <= 2 experts, combine weights sum to ~1
    per_token = jnp.sum(dispatch, axis=(1, 2))
    assert int(jnp.max(per_token)) <= 2
    sums = jnp.sum(combine, axis=(1, 2))
    kept = per_token == 2
    np.testing.assert_allclose(np.asarray(sums[kept]), 1.0, rtol=1e-5)
    # no slot is used twice within an expert
    slot_usage = jnp.sum(dispatch, axis=0)  # (E, C)
    assert int(jnp.max(slot_usage)) <= 1
    assert float(aux) > 0


def test_capacity_drops_overflow_tokens():
    # All tokens prefer expert 0 -> capacity clips most of them.
    gates = jnp.tile(jnp.array([[0.9, 0.1, 0.0, 0.0]]), (32, 1))
    dispatch, combine, _ = mixtral._top2_dispatch(gates, capacity=4)
    assert int(jnp.sum(dispatch[:, 0])) == 4  # expert 0 full
    assert int(jnp.sum(dispatch[:, 1])) == 4  # expert 1 full (top-2)


def test_mixtral_forward_and_train_ep():
    cfg = mixtral.MixtralConfig.tiny(vocab_size=64)
    mesh = mesh_lib.make_mesh({"dp": 2, "ep": 4})
    params = mixtral.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(
        learning_rate=5e-3, warmup_steps=1, total_steps=30))
    state = trainer.init_train_state(params, tx)
    shardings = trainer.state_shardings(
        mesh, mesh_lib.DEFAULT_RULES, mixtral.param_specs(cfg),
        jax.eval_shape(lambda: state))
    state = jax.device_put(state, shardings)
    # experts actually sharded over ep
    assert state.params["layers"]["w_gate"].sharding.spec[1] == "ep"

    def fwd(p, t, constrain):
        return mixtral.forward(cfg, p, t, constrain=constrain,
                               with_aux=True)

    step = trainer.make_train_step(fwd, tx, mesh, mesh_lib.DEFAULT_RULES)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, 64)
    state, m0 = step(state, {"tokens": tokens})
    assert float(m0["aux_loss"]) > 0  # router aux loss flows into training
    for _ in range(10):
        state, m = step(state, {"tokens": tokens})
    assert float(m["loss"]) < float(m0["loss"])


def test_mixtral_cached_decode_matches_forward():
    """Prefill+cached steps must produce the same greedy tokens as
    recomputing the full forward each step — the serving contract
    (reference serves Mixtral via vLLM; here the decode loop is native).
    """
    import jax
    import jax.numpy as jnp

    import dataclasses

    cfg = mixtral.MixtralConfig.tiny(vocab_size=128)
    params = mixtral.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)
    toks = mixtral.decode(cfg, params, prompt, jnp.int32(8),
                          max_tokens=4, max_seq=16)
    assert toks.shape == (2, 4)

    # Incremental-vs-whole consistency: greedy next-token where each
    # step re-evaluates the FULL prefix through the same cache path
    # (fresh cache). Must match the token-by-token decode exactly.
    seq = prompt
    expected = []
    for i in range(4):
        cache = mixtral.init_cache(cfg, 2, 16)
        logits, _ = mixtral.forward_with_cache(
            cfg, params, jnp.pad(seq, ((0, 0), (0, 16 - seq.shape[1]))),
            cache, jnp.int32(0), valid_len=jnp.int32(seq.shape[1]),
            logits_at=jnp.int32(seq.shape[1] - 1))
        nxt = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
        expected.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    expected = jnp.stack(expected, axis=1)
    assert (toks == expected).all(), (toks, expected)

    # Dense top-2 inference routing == capacity-routed training forward
    # whenever capacity never binds (huge capacity_factor => no drops).
    roomy = dataclasses.replace(cfg, capacity_factor=100.0)
    full_logits = mixtral.forward(roomy, params, prompt, with_aux=False)
    cache = mixtral.init_cache(cfg, 2, 16)
    cached_logits, _ = mixtral.forward_with_cache(
        cfg, params, jnp.pad(prompt, ((0, 0), (0, 8))), cache,
        jnp.int32(0), valid_len=jnp.int32(8))
    import numpy as np
    np.testing.assert_allclose(
        np.asarray(cached_logits[:, :8], dtype=np.float32),
        np.asarray(full_logits, dtype=np.float32), atol=0.15, rtol=0.05)


def test_serve_llm_mixtral_endpoint():
    """The serve recipe dispatches to the MoE cache path for mixtral
    configs (batch and streaming share it)."""
    import json as json_lib
    import threading
    import urllib.request

    import jax

    from skypilot_tpu.recipes import serve_llm

    cfg = mixtral.MixtralConfig.tiny(vocab_size=128)
    params = mixtral.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=180)
        body = json_lib.dumps({"prompt": [1, 2, 3],
                               "max_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{httpd.server_address[1]}/generate",
            data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json_lib.loads(resp.read())
        assert len(out["tokens"]) == 4
        assert all(0 <= t < 128 for t in out["tokens"])
    finally:
        httpd.shutdown()


def test_dense_routing_matches_capacity_path():
    """VERDICT r3 weak #6: pin the serving-time dense top-2 routing
    against the training-time capacity path — they must agree EXACTLY
    whenever no token is dropped (ample capacity), which is the
    documented justification for dense routing's existence."""
    cfg = dataclasses.replace(mixtral.MixtralConfig.tiny(),
                              capacity_factor=64.0, dtype=jnp.float32)
    params = mixtral.init(cfg, jax.random.key(0))
    lp = jax.tree.map(lambda a: a[0], params["layers"])
    y = jax.random.normal(jax.random.key(1), (2, 16, cfg.dim),
                          dtype=jnp.float32)
    out_cap, _aux = mixtral._moe_mlp(cfg, y, lp,
                                     lambda a, _spec: a)
    out_dense = mixtral._moe_mlp_dense(cfg, y, lp)
    np.testing.assert_allclose(np.asarray(out_dense),
                               np.asarray(out_cap),
                               rtol=2e-5, atol=2e-5)
