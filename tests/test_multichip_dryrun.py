"""The driver's multichip contract, plus the SPMD-efficiency regression.

VERDICT r2 weak-item 1: the dryrun passed but its stderr logged repeated
``[SPMD] Involuntary full rematerialization`` — XLA replicating whole
tensors to move between shardings (wasted ICI bandwidth every step on
real hardware). Root causes fixed: the embedding gather against an
fsdp-sharded table (now a one-hot matmul under SPMD,
models/llama.py embed_tokens) and a sub-shard-count batch in the
multislice exercise. This test runs the full dryrun in a clean
subprocess and asserts the warning never comes back.
"""
import os
import pathlib
import subprocess
import sys

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.mark.slow
def test_dryrun_multichip_no_involuntary_rematerialization():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.pop("_STPU_DRYRUN_CHILD", None)
    proc = subprocess.run(
        [sys.executable, str(REPO / "__graft_entry__.py"), "--dryrun", "8"],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=str(REPO))
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "dryrun_multichip ok" in proc.stdout
    bad = [ln for ln in proc.stderr.splitlines()
           if "Involuntary full rematerialization" in ln]
    assert not bad, (
        f"{len(bad)} SPMD involuntary-rematerialization warning(s) — a "
        f"sharding transition is forcing XLA to replicate a tensor:\n"
        + "\n".join(b[:300] for b in bad))
