"""RequestRateAutoscaler unit tests with synthetic request timestamps.

Reference analog: tests/test_serve_autoscaler.py.
"""
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve.service_spec import SkyServiceSpec


def _spec(**kw):
    base = dict(min_replicas=1, max_replicas=5, target_qps_per_replica=1.0,
                qps_window_seconds=10, upscale_delay_seconds=5,
                downscale_delay_seconds=20)
    base.update(kw)
    return SkyServiceSpec(**base)


def test_static_spec_uses_base_autoscaler():
    spec = SkyServiceSpec(min_replicas=3)
    a = autoscalers.Autoscaler.from_spec(spec)
    assert type(a) is autoscalers.Autoscaler
    assert a.evaluate_scaling().target_num_replicas == 3


def test_request_rate_upscale_after_delay():
    a = autoscalers.RequestRateAutoscaler(_spec())
    t0 = 1000.0
    # Sustained 3 qps from t0-10 through t0+6: every 10s window sees 30
    # requests -> raw target 3.
    a.collect_request_information(
        [t0 - 10 + k / 3.0 for k in range(48)])
    # Immediately: hysteresis holds at min.
    assert a.evaluate_scaling(now=t0).target_num_replicas == 1
    # Before the upscale delay: still held.
    assert a.evaluate_scaling(now=t0 + 2).target_num_replicas == 1
    # After the delay with sustained load: scales to 3.
    assert a.evaluate_scaling(now=t0 + 6).target_num_replicas == 3


def test_request_rate_respects_max_replicas():
    a = autoscalers.RequestRateAutoscaler(_spec(max_replicas=2))
    t0 = 1000.0
    for dt in (0, 6):
        a.collect_request_information(
            [t0 + dt - i * 0.01 for i in range(500)])
        a.evaluate_scaling(now=t0 + dt)
    assert a.target_num_replicas == 2


def test_request_rate_downscale_slow():
    a = autoscalers.RequestRateAutoscaler(_spec())
    t0 = 1000.0
    a.collect_request_information(
        [t0 - 10 + k / 3.0 for k in range(48)])
    a.evaluate_scaling(now=t0)
    a.evaluate_scaling(now=t0 + 6)
    assert a.target_num_replicas == 3
    # Traffic stops; downscale only after downscale_delay (20s).
    assert a.evaluate_scaling(now=t0 + 16).target_num_replicas == 3
    assert a.evaluate_scaling(now=t0 + 25).target_num_replicas == 3
    assert a.evaluate_scaling(now=t0 + 37).target_num_replicas == 1


def test_burst_does_not_upscale():
    a = autoscalers.RequestRateAutoscaler(_spec())
    t0 = 1000.0
    a.collect_request_information([t0 - i * 0.1 for i in range(100)])
    a.evaluate_scaling(now=t0)           # burst starts the candidate clock
    # Burst is over; window drains before the upscale delay passes.
    assert a.evaluate_scaling(now=t0 + 12).target_num_replicas == 1
    assert a._upscale_candidate_since is None


# ---------------------------------------------------------- spot fallback
def test_plan_all_ondemand_without_spot():
    a = autoscalers.Autoscaler.from_spec(SkyServiceSpec(min_replicas=3))
    plan = a.plan()
    assert (plan.target_spot, plan.target_ondemand) == (0, 3)


def test_plan_pure_spot_service():
    a = autoscalers.Autoscaler.from_spec(SkyServiceSpec(min_replicas=3),
                                         use_spot=True)
    plan = a.plan(num_ready_spot=0)
    assert (plan.target_spot, plan.target_ondemand) == (3, 0)


def test_plan_base_ondemand_fallback_carveout():
    spec = SkyServiceSpec(min_replicas=4,
                          base_ondemand_fallback_replicas=1)
    a = autoscalers.Autoscaler.from_spec(spec, use_spot=True)
    plan = a.plan(num_ready_spot=3)
    assert (plan.target_spot, plan.target_ondemand) == (3, 1)
    # base larger than target: never a negative spot pool.
    spec = SkyServiceSpec(min_replicas=1,
                          base_ondemand_fallback_replicas=3)
    a = autoscalers.Autoscaler.from_spec(spec, use_spot=True)
    plan = a.plan()
    assert (plan.target_spot, plan.target_ondemand) == (0, 1)


def test_plan_dynamic_fallback_preemption_stream():
    """Synthetic preemption stream: ready-spot drops tick over tick ->
    the on-demand pool backfills the gap; spot recovery sheds it."""
    spec = SkyServiceSpec(min_replicas=4,
                          base_ondemand_fallback_replicas=1,
                          dynamic_ondemand_fallback=True)
    a = autoscalers.Autoscaler.from_spec(spec, use_spot=True)
    # Steady state: 3 ready spot + 1 base on-demand.
    plan = a.plan(num_ready_spot=3)
    assert (plan.target_spot, plan.target_ondemand) == (3, 1)
    # Preemption wave: 2 of 3 spot replicas die -> backfill 2 on-demand.
    plan = a.plan(num_ready_spot=1)
    assert (plan.target_spot, plan.target_ondemand) == (3, 3)
    # Total wipeout.
    plan = a.plan(num_ready_spot=0)
    assert (plan.target_spot, plan.target_ondemand) == (3, 4)
    # Spot recovers -> on-demand shed back to the base carve-out.
    plan = a.plan(num_ready_spot=3)
    assert (plan.target_spot, plan.target_ondemand) == (3, 1)


def test_plan_dynamic_fallback_with_autoscaling():
    """dynamic fallback composes with request-rate scaling: the scalar
    target comes from qps, the split from ready-spot."""
    spec = _spec(min_replicas=1, max_replicas=5,
                 dynamic_ondemand_fallback=True)
    a = autoscalers.Autoscaler.from_spec(spec, use_spot=True)
    assert isinstance(a, autoscalers.RequestRateAutoscaler)
    t0 = 1000.0
    a.collect_request_information([t0 - 10 + k / 3.0 for k in range(48)])
    a.evaluate_scaling(now=t0)
    plan = a.plan(now=t0 + 6, num_ready_spot=1)
    assert (plan.target_spot, plan.target_ondemand) == (3, 2)


def test_spec_fallback_yaml_round_trip():
    spec = SkyServiceSpec(min_replicas=3,
                          base_ondemand_fallback_replicas=1,
                          dynamic_ondemand_fallback=True)
    assert spec.use_ondemand_fallback
    back = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert back.base_ondemand_fallback_replicas == 1
    assert back.dynamic_ondemand_fallback
    assert back.min_replicas == 3


# --------------------------------------------------- latency-aware policy
def _burn(fast=None, slow=None, breaching=False):
    return {"degraded": breaching,
            "ttft": {"burn_fast": fast, "burn_slow": slow,
                     "breaching": breaching}}


def test_from_spec_dispatches_latency_policy():
    spec = _spec(scaling_policy="latency")
    a = autoscalers.Autoscaler.from_spec(spec)
    assert type(a) is autoscalers.LatencyAwareAutoscaler
    # Default spec stays on the QPS policy — baseline unchanged.
    assert type(autoscalers.Autoscaler.from_spec(_spec())) is \
        autoscalers.RequestRateAutoscaler


def test_latency_burn_scales_up_one_replica_at_a_time():
    a = autoscalers.LatencyAwareAutoscaler(_spec())
    t0 = 1000.0
    # No QPS pressure at all: target would stay at min.
    a.collect_latency_signals(_burn(fast=2.0, slow=2.0, breaching=True))
    assert a.evaluate_scaling(now=t0).target_num_replicas == 1
    # After the upscale delay: ONE step up, not a jump to max.
    assert a.evaluate_scaling(now=t0 + 6).target_num_replicas == 2
    # Still burning: the next step needs its own delay.
    assert a.evaluate_scaling(now=t0 + 7).target_num_replicas == 2
    assert a.evaluate_scaling(now=t0 + 13).target_num_replicas == 3


def test_latency_burn_respects_max_replicas():
    a = autoscalers.LatencyAwareAutoscaler(_spec(max_replicas=2))
    a.collect_latency_signals(_burn(fast=9.0, slow=9.0, breaching=True))
    t = 1000.0
    for dt in (0, 6, 12, 18, 24):
        a.evaluate_scaling(now=t + dt)
    assert a.target_num_replicas == 2


def test_latency_burn_vetoes_downscale_until_recovered():
    """Scaled up by burn, QPS target says 1: the fleet must NOT shed
    replicas while either window still burns, and the downscale clock
    restarts at recovery (no instant drop on a mid-breach window)."""
    a = autoscalers.LatencyAwareAutoscaler(_spec())
    t0 = 1000.0
    a.collect_latency_signals(_burn(fast=2.0, slow=2.0, breaching=True))
    a.evaluate_scaling(now=t0)
    a.evaluate_scaling(now=t0 + 6)
    assert a.target_num_replicas == 2
    # Fast window recovered, slow still burning: downscale stays vetoed
    # far past downscale_delay_seconds.
    a.collect_latency_signals(_burn(fast=0.1, slow=1.5))
    for dt in (7, 20, 60):
        assert a.evaluate_scaling(
            now=t0 + dt).target_num_replicas == 2
    assert a._downscale_candidate_since is None
    # Fully recovered: the delay must elapse AFTER recovery.
    a.collect_latency_signals(_burn(fast=0.1, slow=0.1))
    assert a.evaluate_scaling(now=t0 + 61).target_num_replicas == 2
    assert a.evaluate_scaling(now=t0 + 70).target_num_replicas == 2
    assert a.evaluate_scaling(now=t0 + 82).target_num_replicas == 1


def test_latency_policy_without_signals_is_pure_qps():
    """No collector feed (STPU_FLEET=0, or warming up): the policy
    degrades to the QPS baseline — None burn is "no pressure"."""
    a = autoscalers.LatencyAwareAutoscaler(_spec())
    t0 = 1000.0
    a.collect_request_information([t0 - 10 + k / 3.0 for k in range(48)])
    a.evaluate_scaling(now=t0)
    assert a.evaluate_scaling(now=t0 + 6).target_num_replicas == 3
    a.collect_latency_signals(_burn(fast=None, slow=None))
    # Traffic stops: downscale proceeds normally (None never vetoes).
    a.evaluate_scaling(now=t0 + 25)
    assert a.evaluate_scaling(now=t0 + 46).target_num_replicas == 1


def test_qps_policy_ignores_latency_signals():
    a = autoscalers.RequestRateAutoscaler(_spec())
    a.collect_latency_signals(_burn(fast=9.0, slow=9.0, breaching=True))
    t0 = 1000.0
    a.evaluate_scaling(now=t0)
    assert a.evaluate_scaling(now=t0 + 6).target_num_replicas == 1


def test_adopt_state_carries_latency_signals():
    old = autoscalers.LatencyAwareAutoscaler(_spec())
    old.collect_latency_signals(_burn(fast=2.0, slow=2.0,
                                      breaching=True))
    old.evaluate_scaling(now=1000.0)
    old.evaluate_scaling(now=1006.0)
    assert old.target_num_replicas == 2
    new = autoscalers.Autoscaler.from_spec(_spec(
        scaling_policy="latency"))
    new.adopt_state(old)
    assert new.target_num_replicas == 2
    assert new._latency_signals == old._latency_signals


def test_spec_scaling_policy_and_slo_yaml_round_trip():
    spec = SkyServiceSpec.from_yaml_config({
        "readiness_probe": "/health",
        "replica_policy": {"min_replicas": 1, "max_replicas": 3,
                           "target_qps_per_replica": 2.0,
                           "scaling_policy": "latency"},
        "slo": {"objectives": [
            {"kind": "ttft", "target": 0.95, "threshold_seconds": 0.5},
            {"kind": "error_rate"},
        ]},
    })
    assert spec.scaling_policy == "latency"
    assert spec.slo_objectives[0]["threshold_seconds"] == 0.5
    back = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert back.scaling_policy == "latency"
    assert back.slo_objectives == spec.slo_objectives
    # Defaulted kinds round-trip with their resolved target.
    assert back.slo_objectives[1] == {"kind": "error_rate",
                                      "target": 0.99}


def test_spec_latency_policy_needs_qps_target():
    import pytest

    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidTaskError, match="latency"):
        SkyServiceSpec.from_yaml_config({
            "readiness_probe": "/health",
            "replica_policy": {"min_replicas": 1, "max_replicas": 3,
                               "scaling_policy": "latency"},
        })


def test_spec_invalid_slo_objective_rejected():
    import pytest

    from skypilot_tpu import exceptions
    with pytest.raises(exceptions.InvalidTaskError, match="threshold"):
        SkyServiceSpec.from_yaml_config({
            "readiness_probe": "/health",
            "slo": {"objectives": [{"kind": "ttft"}]},
        })
