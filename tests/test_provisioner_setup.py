"""SSH-host bring-up orchestration, hermetically.

VERDICT r1 flagged provisioner.setup_agent_runtime as never exercised
(the real path needs cloud SSH hosts). Here each "SSH host" is a
LocalCommandRunner directory — the command strings, wheel shipping,
identity recording, head-only daemon start, and the SSH wait/retry loop
all run for real.
"""
import json

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import provisioner
from skypilot_tpu.provision.common import ClusterInfo, InstanceInfo
from skypilot_tpu.utils import command_runner as runner_lib


def _info(n_hosts=2):
    instances = {
        f"h{i}": InstanceInfo(
            instance_id=f"h{i}", internal_ip=f"10.0.0.{i}",
            external_ip=None, slice_id="slice-0", host_index=i,
            tags={})
        for i in range(n_hosts)
    }
    return ClusterInfo(cluster_name="prov-test", provider_name="gcp",
                       region="us-central1", zone="us-central1-a",
                       instances=instances, head_instance_id="h0",
                       provider_config={})


def _local_runners(tmp_path, monkeypatch):
    dirs = {}

    def fake_ssh_runner(info, inst):
        host_dir = tmp_path / inst.instance_id
        dirs[inst.instance_id] = host_dir
        return runner_lib.LocalCommandRunner(inst.instance_id,
                                             str(host_dir))

    monkeypatch.setattr(provisioner, "_ssh_runner", fake_ssh_runner)
    return dirs


@pytest.mark.usefixtures("tmp_state_dir")
def test_setup_agent_runtime_end_to_end(tmp_path, monkeypatch):
    dirs = _local_runners(tmp_path, monkeypatch)
    # Defang only the pip install; everything else runs for real.
    monkeypatch.setattr(provisioner, "_RUNTIME_INSTALL_CMD", "true")
    monkeypatch.setattr(
        provisioner, "_AGENT_START_CMD",
        "mkdir -p ~/.stpu_agent && touch ~/.stpu_agent/daemon_started")

    info = _info(n_hosts=3)
    identity = {"cluster_name": "prov-test", "provider_name": "gcp",
                "provider_config": {"zone": "us-central1-a"},
                "chips_per_host": 4}
    provisioner.setup_agent_runtime(info, identity)

    for iid, host in dirs.items():
        # Wheel shipped to every host.
        wheels = list((host / ".stpu_wheels").glob("*.whl"))
        assert wheels, f"no wheel on {iid}"
        # Identity recorded verbatim (shell quoting survived).
        recorded = json.loads(
            (host / ".stpu_agent" / "cluster.json").read_text())
        assert recorded == identity
        # Daemon started on the head host ONLY.
        started = (host / ".stpu_agent" / "daemon_started").exists()
        assert started == (iid == "h0"), iid


def test_wait_for_ssh_retries_then_succeeds(monkeypatch):
    attempts = {}

    class FlakyRunner:
        def __init__(self, iid, fail_times):
            self.iid, self.fail_times = iid, fail_times

        def run(self, cmd, **kw):
            n = attempts.get(self.iid, 0)
            attempts[self.iid] = n + 1
            return 255 if n < self.fail_times else 0

    runners = {"h0": FlakyRunner("h0", 0), "h1": FlakyRunner("h1", 2)}
    monkeypatch.setattr(provisioner, "_ssh_runner",
                        lambda info, inst: runners[inst.instance_id])
    monkeypatch.setattr(provisioner.time, "sleep", lambda s: None)
    provisioner.wait_for_ssh(_info(2), timeout=60)
    assert attempts["h1"] == 3  # two failures + one success
    assert attempts["h0"] == 1  # already-up host not re-polled


def test_wait_for_ssh_times_out(monkeypatch):
    class DeadRunner:
        def run(self, cmd, **kw):
            return 255

    monkeypatch.setattr(provisioner, "_ssh_runner",
                        lambda info, inst: DeadRunner())
    monkeypatch.setattr(provisioner.time, "sleep", lambda s: None)
    with pytest.raises(exceptions.ProvisionError, match="SSH not"):
        provisioner.wait_for_ssh(_info(2), timeout=0)
