"""Fault-tolerant serving: chaos tests driven by the deterministic
fault-injection harness (skypilot_tpu/utils/fault_injection.py).

The stories pinned here (ISSUE 4 acceptance):
  * a pre-first-byte replica failure is retried on another replica —
    the client sees a complete 200, never a 502, and the circuit
    breaker ejects the dead replica ahead of the controller's probes;
  * an engine-loop crash flips the replica /health endpoint to 503,
    the supervisor restarts the engine with fresh state, and traffic
    recovers;
  * scaling down a replica with an in-flight token stream completes
    that stream before termination (graceful drain);
plus the satellites: aborted-stream accounting, the LB body cap, probe
anti-flap, and the swallowed-exception lint.
"""
import http.client
import http.server
import json
import socket
import socketserver
import threading
import time
import urllib.error
import urllib.request

import pytest

from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve.load_balancing_policies import (
    PrefixAffinityPolicy, RoundRobinPolicy)
from skypilot_tpu.utils import fault_injection as fi


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


# ------------------------------------------------------------ fixtures
class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        pass    # mid-stream deaths are intentional here; keep CI quiet


class _OkHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    hits = None     # set per test to a list

    def log_message(self, *a):
        pass

    def _ok(self):
        if self.hits is not None:
            self.hits.append(self.path)
        body = b'{"ok": true}'
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    do_GET = _ok

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        self.rfile.read(length)
        self._ok()


def _start(handler_cls):
    server = _Server(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _get_code(url, timeout=10):
    try:
        return _get(url, timeout=timeout)[0]
    except urllib.error.HTTPError as e:
        return e.code


# ================================================== fault-injection unit
def test_fault_spec_parse_and_modes():
    rules = fi.parse_spec(
        "lb.upstream:error:p=0.5;engine.step:raise:times=1;"
        "replica.probe:delay:s=0.01")
    by_point = {r.point: r for r in rules}
    assert by_point["lb.upstream"].p == 0.5
    assert by_point["engine.step"].times == 1
    assert by_point["replica.probe"].mode == "delay"
    for bad in ("engine.step", "x:explode", "x:raise:p=nope",
                "x:raise:frobnicate=1"):
        with pytest.raises(fi.FaultSpecError):
            fi.parse_spec(bad)


def test_fire_times_budget_and_enabled_flag():
    assert not fi.ENABLED
    fi.fire("engine.step")           # unarmed: no-op
    fi.activate("engine.step", times=2)
    assert fi.ENABLED
    for _ in range(2):
        with pytest.raises(fi.InjectedFault):
            fi.fire("engine.step")
    fi.fire("engine.step")           # budget exhausted: no-op
    assert fi.fires("engine.step") == 2
    fi.clear()
    assert not fi.ENABLED


def test_injected_fault_is_connection_error():
    # The choke points sit behind except-clauses that catch
    # connection-shaped failures; injection must ride the SAME path.
    assert issubclass(fi.InjectedFault, ConnectionError)


def test_probabilistic_faults_reproducible_under_seed():
    def pattern():
        fi.configure("p.test:raise:p=0.5", seed=1234)
        out = []
        for _ in range(32):
            try:
                fi.fire("p.test")
                out.append(0)
            except fi.InjectedFault:
                out.append(1)
        return out

    first, second = pattern(), pattern()
    assert first == second              # seeded chaos replays exactly
    assert 0 < sum(first) < 32          # and actually mixes outcomes
    fi.configure("p.test:raise:p=0.5", seed=99)
    third = []
    for _ in range(32):
        try:
            fi.fire("p.test")
            third.append(0)
        except fi.InjectedFault:
            third.append(1)
    assert third != first               # a new seed is a new run


# ====================================================== policy exclusion
def test_round_robin_exclusion():
    p = RoundRobinPolicy()
    p.set_ready_replicas(["http://a", "http://b"])
    assert p.select_replica(exclude={"http://a"}) == "http://b"
    assert p.select_replica(exclude={"http://a", "http://b"}) is None
    # No exclusion: still rotates.
    got = {p.select_replica() for _ in range(4)}
    assert got == {"http://a", "http://b"}


def test_prefix_affinity_exclusion_deterministic():
    p = PrefixAffinityPolicy()
    urls = [f"http://r{i}" for i in range(3)]
    p.set_ready_replicas(urls)
    req = {"path": "/generate",
           "body": json.dumps({"prompt": list(range(64)),
                               "max_tokens": 4}).encode()}
    owner = p.select_replica(req)
    p.report_done(owner)
    alt1 = p.select_replica(req, exclude={owner})
    p.report_done(alt1)
    alt2 = p.select_replica(req, exclude={owner})
    p.report_done(alt2)
    assert alt1 == alt2 != owner     # retries spill deterministically
    assert p.select_replica(req, exclude=set(urls)) is None
    # Excluded selections must not leak in-flight slots.
    assert all(v == 0 for v in p._inflight.values())


# ================================================= circuit breaker unit
def test_circuit_breaker_state_machine():
    br = lb_lib.CircuitBreaker(threshold=2, backoff_base=0.05,
                               backoff_cap=0.05, jitter=0.0, seed=7)
    url = "http://r1"
    br.record_failure(url)
    assert br.state(url) == "closed"
    br.record_failure(url)
    assert br.state(url) == "open"          # threshold hit: ejected
    assert br.blocked([url]) == {url}
    time.sleep(0.08)
    assert br.blocked([url]) == set()       # backoff over: half-open
    assert br.state(url) == "half_open"
    br.record_failure(url)                  # failed probe: re-open
    assert br.state(url) == "open"
    time.sleep(0.12)                        # doubled backoff (capped)
    assert br.blocked([url]) == set()
    br.record_success(url)
    assert br.state(url) == "closed"        # full cycle closed again
    # The whole cycle is observable in the exposition.
    from skypilot_tpu.observability import metrics
    assert 'stpu_lb_breaker_state{replica="http://r1"} 0' \
        in metrics.render()
    assert lb_lib._BREAKER_EJECTIONS.labels(replica=url).get() >= 1
    br.prune([])
    assert br.state(url) == "closed"


# ======================================================== LB retry e2e
def test_lb_retries_dead_replica_and_breaker_ejects():
    """Acceptance (a): with one dead replica in rotation every request
    still completes 200 via retry; after the failure threshold the
    breaker ejects the dead replica so later requests don't even pay
    the failed connect."""
    hits = []
    handler = type("H", (_OkHandler,), {"hits": hits})
    server, ok_url = _start(handler)
    dead = f"http://127.0.0.1:{_free_port()}"
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([ok_url, dead])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    lb.breaker.threshold = 2
    lb.breaker.backoff_base = 30.0        # stays open for the test
    retries0 = lb_lib._RETRIES.get()
    try:
        for _ in range(8):
            status, body = _get(
                f"http://127.0.0.1:{lb.server_address[1]}/x")
            assert status == 200 and json.loads(body) == {"ok": True}
        assert lb_lib._RETRIES.get() > retries0
        assert lb.breaker.state(dead) == "open"
        assert lb_lib._BREAKER_EJECTIONS.labels(
            replica=dead).get() >= 1
        # Ejected: requests stop trying the dead replica entirely.
        r1 = lb_lib._RETRIES.get()
        for _ in range(4):
            status, _ = _get(
                f"http://127.0.0.1:{lb.server_address[1]}/x")
            assert status == 200
        assert lb_lib._RETRIES.get() == r1
        # Breaker + retry families ride the LB's own /metrics.
        _, text = _get(
            f"http://127.0.0.1:{lb.server_address[1]}/metrics")
        text = text.decode()
        assert f'stpu_lb_breaker_state{{replica="{dead}"}} 1' in text
        assert "stpu_lb_upstream_retries_total" in text
        assert "stpu_lb_breaker_ejections_total" in text
    finally:
        lb.shutdown()
        server.shutdown()


def test_lb_breaker_half_open_readmits_recovered_replica():
    hits = []
    handler = type("H", (_OkHandler,), {"hits": hits})
    server, ok_url = _start(handler)
    port = _free_port()
    flaky = f"http://127.0.0.1:{port}"
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([ok_url, flaky])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    lb.breaker.threshold = 2
    lb.breaker.backoff_base = 0.2
    lb.breaker.backoff_cap = 0.2
    revived = None
    try:
        for _ in range(8):
            assert _get(
                f"http://127.0.0.1:{lb.server_address[1]}/x")[0] == 200
        assert lb.breaker.state(flaky) == "open"
        # The replica comes back on the same port; after the backoff a
        # half-open probe (live traffic) closes the circuit.
        revived = _Server(("127.0.0.1", port), handler)
        threading.Thread(target=revived.serve_forever,
                         daemon=True).start()
        time.sleep(0.3)
        deadline = time.time() + 10
        while time.time() < deadline:
            assert _get(
                f"http://127.0.0.1:{lb.server_address[1]}/x")[0] == 200
            if lb.breaker.state(flaky) == "closed":
                break
            time.sleep(0.05)
        assert lb.breaker.state(flaky) == "closed"
    finally:
        lb.shutdown()
        server.shutdown()
        if revived is not None:
            revived.shutdown()


def test_lb_retries_503_when_peer_available():
    """A draining/warming replica answers 503; with a healthy peer in
    rotation the LB re-routes instead of passing the 503 through (the
    drain-gap closer); with NO healthy peer the 503 passes through."""

    class _Unavailable(_OkHandler):
        def _ok(self):
            body = b'{"error": "draining"}'
            self.send_response(503)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        do_GET = _ok

    bad_server, bad_url = _start(_Unavailable)
    ok_server, ok_url = _start(type("H", (_OkHandler,), {}))
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([bad_url, ok_url])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    try:
        for _ in range(4):
            assert _get(
                f"http://127.0.0.1:{lb.server_address[1]}/x")[0] == 200
    finally:
        lb.shutdown()
    policy2 = RoundRobinPolicy()
    policy2.set_ready_replicas([bad_url])
    lb2 = lb_lib.run_load_balancer(0, policy2, lb_lib.RequestRecorder())
    try:
        assert _get_code(
            f"http://127.0.0.1:{lb2.server_address[1]}/x") == 503
    finally:
        lb2.shutdown()
        bad_server.shutdown()
        ok_server.shutdown()


# ============================================ aborted-stream accounting
def test_lb_mid_stream_death_counts_aborted_and_returns_slot():
    """Satellite: a replica dying MID-stream is recorded as
    code="upstream_aborted" (not a clean 200, and not the
    client_closed code — the REPLICA died, the client was still
    there), is NOT retried (the status line already went out; this is
    a GET, so the stream journal doesn't apply either), and
    report_done still returns the in-flight slot."""

    class _DieMidStream(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            data = b"data: one\n\n"
            self.wfile.write(f"{len(data):x}\r\n".encode())
            self.wfile.write(data + b"\r\n")
            self.wfile.flush()
            # Die without the chunked terminator: an abrupt close the
            # LB sees as IncompleteRead mid-body.
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            self.close_connection = True

    class _Recording(RoundRobinPolicy):
        def __init__(self):
            super().__init__()
            self.done = []

        def report_done(self, url):
            self.done.append(url)

    server, url = _start(_DieMidStream)
    policy = _Recording()
    policy.set_ready_replicas([url])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    lb.breaker.threshold = 1       # one mid-stream death must eject
    aborted0 = lb_lib._REQUESTS.labels(method="GET",
                                       code="upstream_aborted").get()
    ok0 = lb_lib._REQUESTS.labels(method="GET", code="200").get()
    retries0 = lb_lib._RETRIES.get()
    try:
        conn = http.client.HTTPConnection(
            "127.0.0.1", lb.server_address[1], timeout=10)
        conn.request("GET", "/stream")
        resp = conn.getresponse()
        assert resp.status == 200      # the 2xx line DID go out
        got = b""
        with pytest.raises((http.client.HTTPException, ConnectionError,
                            OSError)):
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    # Truncated chunked stream surfaces as an error on
                    # some paths and a short read on others; normalize.
                    raise http.client.IncompleteRead(got)
                got += chunk
        conn.close()
        deadline = time.time() + 5
        while time.time() < deadline and lb_lib._REQUESTS.labels(
                method="GET", code="upstream_aborted").get() == aborted0:
            time.sleep(0.05)
        assert lb_lib._REQUESTS.labels(
            method="GET", code="upstream_aborted").get() == aborted0 + 1
        assert lb_lib._REQUESTS.labels(
            method="GET", code="200").get() == ok0
        assert lb_lib._RETRIES.get() == retries0   # no mid-stream retry
        assert policy.done == [url]                # slot returned
        # An accept-then-die replica feeds the breaker too: success is
        # only recorded after the WHOLE stream proxies, so mid-stream
        # deaths accumulate instead of self-neutralizing.
        assert lb.breaker.state(url) == "open"
    finally:
        lb.shutdown()
        server.shutdown()


# ============================================================= body cap
def test_lb_request_body_cap_413():
    hits = []
    handler = type("H", (_OkHandler,), {"hits": hits})
    server, url = _start(handler)
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([url])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    lb.RequestHandlerClass.max_body_bytes = 1024
    try:
        big = b"x" * 4096
        req = urllib.request.Request(
            f"http://127.0.0.1:{lb.server_address[1]}/gen", data=big,
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 413
        assert hits == []              # never reached a replica
        # An in-cap body still proxies.
        req = urllib.request.Request(
            f"http://127.0.0.1:{lb.server_address[1]}/gen",
            data=b"y" * 512, method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 200
        assert hits == ["/gen"]
    finally:
        lb.shutdown()
        server.shutdown()


# ===================================================== engine supervision
class _CrashOnStart:
    """Engine stub whose compute loop is dead on arrival — drives the
    supervisor's restart/permanent-down ladder without paying real
    model setup per restart."""

    def __init__(self):
        self._failed = None

    def start(self):
        self._failed = "InjectedFault: boom"
        return self

    def submit(self, *a, **k):
        from skypilot_tpu.serve import decode_engine
        raise decode_engine.EngineError(f"engine failed: {self._failed}")

    def drain(self):
        pass

    def in_flight(self):
        return 0

    def shutdown(self):
        pass


def test_supervisor_permanent_down_after_max_fast_failures():
    from skypilot_tpu.serve import decode_engine
    sup = decode_engine.EngineSupervisor(
        _CrashOnStart, max_restarts=2, backoff_base=0.01,
        backoff_cap=0.02, fast_failure_seconds=10.0,
        poll_interval=0.01).start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline and not sup.permanently_down:
            time.sleep(0.02)
        assert sup.permanently_down
        assert sup.restarts == 2       # tried exactly max_restarts times
        assert not sup.healthy()
        with pytest.raises(decode_engine.EngineError,
                           match="permanently down"):
            sup.submit([1], max_tokens=1)
    finally:
        sup.shutdown()


def _tiny_llm():
    import jax
    from skypilot_tpu.models import llama
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    return cfg, params


def test_engine_crash_health_503_supervisor_restart_recovers():
    """Acceptance (b): crash the engine loop via the fault harness →
    /health flips to 503 (no zombie replica) → the supervisor restarts
    the engine with fresh state → the next request succeeds and is
    bit-identical to pre-crash output."""
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import decode_engine

    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_restart_backoff=0.5)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=120)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    restarts0 = decode_engine._RESTARTS.get()

    def generate():
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": [1, 2, 3],
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}")

    try:
        status, payload = generate()
        assert status == 200 and len(payload["tokens"]) == 4
        baseline = payload["tokens"]

        fi.activate("engine.step", times=1)
        status, payload = generate()
        assert status == 503           # clean EngineError, not a hang
        assert fi.fires("engine.step") == 1
        # Zombie-killer: the health endpoint must report the dead
        # engine (the HTTP process itself is perfectly alive).
        deadline = time.time() + 5
        saw_unhealthy = False
        while time.time() < deadline:
            if _get_code(base + "/health") == 503:
                saw_unhealthy = True
                break
            time.sleep(0.01)
        assert saw_unhealthy, "dead engine never surfaced on /health"
        # Supervisor restarts (0.5s backoff) and health recovers.
        deadline = time.time() + 30
        while time.time() < deadline:
            if _get_code(base + "/health") == 200:
                break
            time.sleep(0.05)
        assert _get_code(base + "/health") == 200
        status, payload = generate()
        assert status == 200
        assert payload["tokens"] == baseline   # fresh cache, same math
        assert httpd.engine.restarts >= 1
        assert decode_engine._RESTARTS.get() >= restarts0 + 1
    finally:
        fi.clear()
        httpd.engine.shutdown()
        httpd.shutdown()


def test_engine_drain_finishes_inflight_rejects_new():
    from skypilot_tpu.serve import decode_engine

    cfg, params = _tiny_llm()
    engine = decode_engine.DecodeEngine(cfg, params, slots=2,
                                        max_seq=128,
                                        prefill_chunk=16).start()
    try:
        engine.warmup()
        # Slow each decode step so the drain demonstrably overlaps a
        # live stream.
        fi.activate("engine.step", mode="delay", delay=0.02)
        req = engine.submit([1, 2, 3], max_tokens=12)
        it = req.stream(timeout=60)
        first = next(it)
        engine.drain()
        with pytest.raises(decode_engine.EngineError, match="draining"):
            engine.submit([1], max_tokens=2)
        toks = [first] + list(it)
        assert len(toks) == 12         # in-flight stream ran to the end
        deadline = time.time() + 5
        while time.time() < deadline and engine.in_flight():
            time.sleep(0.02)
        assert engine.in_flight() == 0
    finally:
        fi.clear()
        engine.shutdown()


# ================================================== graceful drain e2e
@pytest.mark.usefixtures("tmp_state_dir")
def test_scale_down_drains_inflight_stream():
    """Acceptance (c): scale_down of a READY replica with a live SSE
    stream completes the stream (every token + [DONE]) before the
    cluster is terminated, and the drain lifecycle lands in the event
    log."""
    from skypilot_tpu.observability import events
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.serve import replica_managers, serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    from skypilot_tpu.task import Task

    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=120)
    port = httpd.server_address[1]
    url = f"http://127.0.0.1:{port}"

    spec = SkyServiceSpec(readiness_path="/health", min_replicas=1,
                          initial_delay_seconds=60,
                          drain_timeout_seconds=30)
    task = Task("drain-svc", run="true")
    task.set_resources(Resources(cloud="local"))
    task.service = spec
    mgr = replica_managers.SkyPilotReplicaManager("svc-drain", spec,
                                                  task)
    info = replica_managers.ReplicaInfo(1, "svc-drain-replica-1", port,
                                        spec=spec)
    info.url = url
    info.status = ReplicaStatus.READY
    mgr.replicas[1] = info

    results = {}

    def consume():
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=120)
        conn.request("POST", "/generate",
                     body=json.dumps({"prompt": [1, 2, 3],
                                      "max_tokens": 30,
                                      "stream": True}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        chunks = []
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            chunks.append(chunk)
        results["text"] = b"".join(chunks).decode()
        results["done_at"] = time.monotonic()
        conn.close()

    # Slow decode steps so the stream is demonstrably in flight when
    # the drain starts.
    fi.activate("engine.step", mode="delay", delay=0.05)
    client = threading.Thread(target=consume, daemon=True)
    client.start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            _, body = _get(url + "/drain")
            if json.loads(body)["in_flight"] >= 1:
                break
            time.sleep(0.02)
        else:
            pytest.fail("stream never registered in flight")

        mgr.scale_down(1, sync=True)       # auto-drains (READY + spec)
        terminated_at = time.monotonic()
        client.join(timeout=60)
        assert "done_at" in results, "client stream never finished"
        text = results["text"]
        tokens = [ln for ln in text.splitlines()
                  if ln.startswith("data: {")]
        assert len(tokens) == 30, f"truncated stream: {len(tokens)}/30"
        assert "data: [DONE]" in text      # clean SSE terminator
        # The stream finished BEFORE termination proceeded.
        assert results["done_at"] <= terminated_at
        # Replica record cleaned up; lifecycle events recorded.
        assert serve_state.get_replicas("svc-drain") == []
        evs = [e["event"] for e in events.read(kind="replica",
                                               name="svc-drain/1",
                                               limit=None)]
        assert "drain_start" in evs and "drain_complete" in evs
        # Draining replica rejects NEW work (the LB re-routes on 503).
        req = urllib.request.Request(
            url + "/generate",
            data=json.dumps({"prompt": [5], "max_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 503
    finally:
        fi.clear()
        httpd.engine.shutdown()
        httpd.shutdown()


@pytest.mark.usefixtures("tmp_state_dir")
def test_scale_down_without_drain_support_terminates_immediately():
    """A replica whose server has no /drain endpoint (plain HTTP
    servers, pre-drain tasks) degrades to the old kill-immediately
    path instead of stalling out the drain deadline."""
    from skypilot_tpu.observability import events
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    from skypilot_tpu.task import Task

    class _GetOnly(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        do_GET = _OkHandler._ok
        hits = None
        # no do_POST: POST /drain gets a 501, like python -m http.server

    server, url = _start(_GetOnly)
    spec = SkyServiceSpec(readiness_path="/", min_replicas=1,
                          drain_timeout_seconds=30)
    task = Task("nodrain-svc", run="true")
    task.set_resources(Resources(cloud="local"))
    task.service = spec
    mgr = replica_managers.SkyPilotReplicaManager("svc-nodrain", spec,
                                                  task)
    info = replica_managers.ReplicaInfo(
        1, "svc-nodrain-replica-1",
        server.server_address[1], spec=spec)
    info.url = url
    info.status = ReplicaStatus.READY
    mgr.replicas[1] = info
    t0 = time.monotonic()
    mgr.scale_down(1, sync=True)
    assert time.monotonic() - t0 < 10    # no 30s drain stall
    evs = [e["event"] for e in events.read(kind="replica",
                                           name="svc-nodrain/1",
                                           limit=None)]
    assert "drain_unsupported" in evs
    server.shutdown()


def test_serve_llm_drain_endpoint_legacy_path():
    """The legacy (engine_slots=0) path honors /drain too: admissions
    stop, in-flight handler count is reported."""
    from skypilot_tpu.recipes import serve_llm

    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=120)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        req = urllib.request.Request(base + "/drain", data=b"{}",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = json.loads(resp.read())
        assert payload["draining"] is True
        assert payload["in_flight"] == 0
        gen = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": [1], "max_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(gen, timeout=10)
        assert exc.value.code == 503
    finally:
        httpd.shutdown()


@pytest.mark.usefixtures("tmp_state_dir")
def test_recovery_finishes_interrupted_drain():
    """A controller crash mid-drain leaves a DRAINING row; the
    restarted controller must FINISH the teardown, not re-adopt the
    husk as STARTING — its server's drain flag is irreversible, so an
    adopted husk would probe READY while refusing every request (a
    zombie that also keeps billing)."""
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.serve import replica_managers, serve_state
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    from skypilot_tpu.task import Task

    serve_state.upsert_replica("svc-rec", 1, "svc-rec-replica-1",
                               ReplicaStatus.DRAINING,
                               "http://127.0.0.1:9",   # long gone
                               launched_at=time.time())
    spec = SkyServiceSpec(readiness_path="/", min_replicas=1,
                          drain_timeout_seconds=30)
    task = Task("rec-svc", run="true")
    task.set_resources(Resources(cloud="local"))
    task.service = spec
    mgr = replica_managers.SkyPilotReplicaManager("svc-rec", spec, task)
    deadline = time.time() + 30
    while time.time() < deadline:
        if (1 not in mgr.replicas and
                serve_state.get_replicas("svc-rec") == []):
            break
        time.sleep(0.1)
    assert 1 not in mgr.replicas, "DRAINING husk was adopted"
    assert serve_state.get_replicas("svc-rec") == []


# ====================================================== probe anti-flap
@pytest.mark.usefixtures("tmp_state_dir")
def test_probe_anti_flap_requires_success_streak():
    """Satellite: after a probe failure a replica needs 2 consecutive
    successes before re-admission — one lucky probe must not bounce an
    oscillating replica back into the LB rotation."""
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    from skypilot_tpu.task import Task

    server, url = _start(type("H", (_OkHandler,), {}))
    spec = SkyServiceSpec(readiness_path="/", min_replicas=1,
                          initial_delay_seconds=0)
    task = Task("flap-svc", run="true")
    task.set_resources(Resources(cloud="local"))
    task.service = spec
    mgr = replica_managers.SkyPilotReplicaManager("svc-flap", spec,
                                                  task)
    info = replica_managers.ReplicaInfo(
        1, "svc-flap-replica-1", server.server_address[1], spec=spec)
    info.url = url
    info.status = ReplicaStatus.READY
    info.first_ready_at = time.time()
    mgr.replicas[1] = info
    try:
        with fi.inject("replica.probe", times=1):
            mgr._probe_one(info)
        assert info.status == ReplicaStatus.NOT_READY
        mgr._probe_one(info)     # 1st success: still quarantined
        assert info.status == ReplicaStatus.NOT_READY
        mgr._probe_one(info)     # 2nd consecutive success: re-admitted
        assert info.status == ReplicaStatus.READY
        # A failure mid-streak resets the counter.
        with fi.inject("replica.probe", times=1):
            mgr._probe_one(info)
        assert info.status == ReplicaStatus.NOT_READY
        mgr._probe_one(info)
        assert info.status == ReplicaStatus.NOT_READY
    finally:
        server.shutdown()


# ================================================= gang-replica chaos
def _spawn_gang_replica(port, env_extra=None, hosts=2,
                        extra_args=None):
    """2-process gang replica (serve_llm self-spawn mode), unsharded
    (tp=1) so the fault-path tests pay no mesh-compile tax."""
    import pathlib
    import subprocess
    import sys
    env = dict(__import__("os").environ)
    env["PYTHONPATH"] = str(
        pathlib.Path(__file__).resolve().parent.parent)
    env["STPU_GANG_HB_TIMEOUT"] = "2"
    env.update(env_extra or {})
    return subprocess.Popen(
        [sys.executable, "-m", "skypilot_tpu.recipes.serve_llm",
         "--model", "tiny", "--port", str(port),
         "--replica-hosts", str(hosts)] + list(extra_args or ()),
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)


def _wait_code(url, want, timeout=240):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            code = _get_code(url, timeout=5)
        except (urllib.error.URLError, ConnectionError, OSError):
            code = None      # not listening yet / mid-restart
        if code == want:
            return True
        time.sleep(0.25)
    return False


def _gang_members(port):
    return json.loads(
        _get(f"http://127.0.0.1:{port}/gang")[1])["members"]


def _pid_alive(pid):
    import os
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


@pytest.mark.usefixtures("tmp_state_dir")
def test_gang_drain_and_shutdown_leave_no_orphan_followers():
    """POST /drain propagates to the follower's engine (gang-wide
    drain), and SIGTERM teardown reaps every self-spawned follower —
    scale-down must never orphan a gang member process."""
    import os
    import signal as signal_lib
    import subprocess
    port = _free_port()
    proc = _spawn_gang_replica(port)
    base = f"http://127.0.0.1:{port}"
    try:
        assert _wait_code(base + "/health", 200), "gang never ready"
        follower_pids = [m["pid"] for m in _gang_members(port)
                         if m["role"] == "follower"]
        assert follower_pids and all(_pid_alive(p)
                                     for p in follower_pids)
        # Drain: replica refuses new work, gang stays up (draining is
        # not degradation — /gang keeps answering).
        req = urllib.request.Request(base + "/drain", data=b"{}",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            payload = json.loads(resp.read())
        assert payload["draining"] is True
        gen = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": [1], "max_tokens": 2}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(gen, timeout=10)
            assert False, "draining replica accepted work"
        except urllib.error.HTTPError as e:
            assert e.code == 503
        # SIGTERM: the leader broadcasts shutdown + reaps followers.
        os.kill(proc.pid, signal_lib.SIGTERM)
        proc.wait(timeout=30)
        assert proc.returncode == 143
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and any(
                _pid_alive(p) for p in follower_pids):
            time.sleep(0.2)
        leaked = [p for p in follower_pids if _pid_alive(p)]
        assert not leaked, f"orphaned follower processes: {leaked}"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


@pytest.mark.usefixtures("tmp_state_dir")
def test_gang_follower_kill_via_chaos_seam_recovers():
    """A seeded `gang.host` kill fault SIGKILLs the follower at its
    first mirrored submission (the same seam host_wrapper fires for
    gang-launched hosts): host 0's /health flips 503, the whole-gang
    supervisor restart respawns the member, and traffic recovers."""
    port = _free_port()
    # The fault spec rides the leader's env into the self-spawned
    # follower; the leader itself never fires gang.host.
    proc = _spawn_gang_replica(
        port, env_extra={"STPU_FAULTS": "gang.host:kill:times=1"})
    base = f"http://127.0.0.1:{port}"
    try:
        assert _wait_code(base + "/health", 200), "gang never ready"
        before = [m["pid"] for m in _gang_members(port)
                  if m["role"] == "follower"]
        gen = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": [1, 2],
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        # The broadcast of this admission kills the follower; host 0's
        # own engine still answers the request.
        with urllib.request.urlopen(gen, timeout=120) as resp:
            assert resp.status == 200
        assert _wait_code(base + "/health", 503, timeout=30), \
            "/health never flipped after the chaos kill"
        assert _wait_code(base + "/health", 200, timeout=120), \
            "whole-gang restart never recovered"
        after = [m["pid"] for m in _gang_members(port)
                 if m["role"] == "follower"]
        assert after and after != before
        with urllib.request.urlopen(gen, timeout=120) as resp:
            assert resp.status == 200
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except Exception:  # noqa: stpu-except — best-effort teardown of a test subprocess
                proc.kill()


# ====================================== preemption-notice proactive drain
def test_preempt_notice_watch_sets_event_and_counter():
    """Unit: the metadata watcher treats an injected
    ``replica.preempt_notice`` fault AS the provider's notice — it
    sets the shared event (the /health surface), counts the notice,
    and stops (the notice is terminal for the replica)."""
    from skypilot_tpu.recipes import serve_llm
    notice = threading.Event()
    before = serve_llm._PREEMPT_NOTICES.get()
    fi.activate("replica.preempt_notice")
    try:
        serve_llm.preempt_notice_watch(notice, poll=0.01)
    finally:
        fi.clear()
    assert notice.is_set()
    assert serve_llm._PREEMPT_NOTICES.get() == before + 1


@pytest.mark.usefixtures("tmp_state_dir")
def test_preempt_notice_probe_drains_ahead_of_kill():
    """Tentpole (3) at the manager layer: a replica that is serving
    fine but advertising ``preempt_notice: true`` on /health is
    flipped DRAINING by the very probe that saw the notice —
    synchronously, so the same controller tick already counts it
    not-alive and launches the replacement (replace-ahead) — with the
    notice in the event log and the replica out of the ready set."""
    from skypilot_tpu.observability import events
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.serve.serve_state import ReplicaStatus
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    from skypilot_tpu.task import Task

    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=120)
    port = httpd.server_address[1]
    url = f"http://127.0.0.1:{port}"

    spec = SkyServiceSpec(readiness_path="/health", min_replicas=1,
                          initial_delay_seconds=60,
                          drain_timeout_seconds=30)
    task = Task("preempt-svc", run="true")
    task.set_resources(Resources(cloud="local"))
    task.service = spec
    mgr = replica_managers.SkyPilotReplicaManager("svc-preempt", spec,
                                                  task)
    info = replica_managers.ReplicaInfo(1, "svc-preempt-replica-1",
                                        port, spec=spec)
    info.url = url
    info.status = ReplicaStatus.READY
    info.first_ready_at = time.time()
    mgr.replicas[1] = info
    try:
        # Healthy, no notice: the probe keeps it READY.
        _, body = _get(url + "/health")
        assert "preempt_notice" not in json.loads(body)
        mgr._probe_one(info)
        assert info.status == ReplicaStatus.READY

        # The provider's notice lands (what preempt_notice_watch sets
        # when the replica.preempt_notice fault fires): /health keeps
        # answering 200 — the replica is NOT sick — but carries the
        # notice.
        httpd.RequestHandlerClass.server_ctx["preempt_notice"].set()
        code, body = _get(url + "/health")
        assert code == 200
        assert json.loads(body)["preempt_notice"] is True

        mgr._probe_one(info)
        # DRAINING the moment the probe returns — not after a
        # teardown thread got scheduled — so this tick's reconcile
        # already sees alive < target and replaces ahead of the kill.
        assert info.status == ReplicaStatus.DRAINING
        assert not ReplicaStatus.DRAINING.is_alive()
        assert url not in mgr.ready_urls()
        evs = [e["event"] for e in events.read(kind="replica",
                                               name="svc-preempt/1",
                                               limit=None)]
        assert "preempt_notice" in evs
        # A second probe mid-drain must not double-drain.
        mgr._probe_one(info)
        assert evs.count("preempt_notice") == 1
        # The husk drains through the normal teardown (drain_start in
        # the log; the record survives for postmortem).
        deadline = time.time() + 30
        while time.time() < deadline:
            evs = [e["event"] for e in events.read(
                kind="replica", name="svc-preempt/1", limit=None)]
            if "drain_complete" in evs:
                break
            time.sleep(0.1)
        assert "drain_start" in evs
    finally:
        httpd.engine.shutdown()
        httpd.shutdown()


# ============================================ gang SIGKILL + LB resume
@pytest.mark.usefixtures("tmp_state_dir")
def test_gang_sigkill_mid_stream_lb_resume_bit_identical():
    """ISSUE 19 acceptance: a 2-host gang replica SIGKILLed (the real
    preemption, no drain, no goodbye) mid-stream with speculative
    decode + paged int8 KV on — the LB's journal resumes the stream
    on a peer replica and the CLIENT's bytes are bit-identical to the
    uninterrupted run, greedy and seeded."""
    flags = ["--kv-paged", "1", "--kv-quant", "1", "--spec-k", "3",
             "--spec-ngram", "2"]
    port_a, port_b = _free_port(), _free_port()
    # A (the victim): 2-host gang, decode slowed through the fault
    # seam so the SIGKILL demonstrably lands mid-stream. B (the
    # survivor): same model + config, full speed.
    proc_a = _spawn_gang_replica(
        port_a, hosts=2, extra_args=flags,
        env_extra={"STPU_FAULTS": "engine.step:delay:s=0.04"})
    proc_b = _spawn_gang_replica(port_b, hosts=1, extra_args=flags)
    a = f"http://127.0.0.1:{port_a}"
    b = f"http://127.0.0.1:{port_b}"

    class _Ordered:
        def set_ready_replicas(self, urls):
            pass

        def select_replica(self, request=None, exclude=None):
            for url in (a, b):
                if url not in (exclude or ()):
                    return url
            return None

        def report_done(self, url):
            pass

        def ready_replicas(self):
            return [a, b]

    def stream_bytes(base, doc, sink=None, timeout=120):
        conn = http.client.HTTPConnection(
            *base.split("//", 1)[1].split(":"), timeout=timeout)
        try:
            conn.request("POST", "/generate", body=json.dumps(doc),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            chunks = []
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    break
                chunks.append(chunk)
                if sink is not None:
                    sink.append(chunk)
            return resp.status, b"".join(chunks)
        finally:
            conn.close()

    lb_handler = type("Handler", (lb_lib._ProxyHandler,), {
        "policy": _Ordered(), "recorder": lb_lib.RequestRecorder(),
        "breaker": None, "upstream_timeout": 300.0,
        "journal_account": lb_lib.JournalAccount()})
    lb = lb_lib._ThreadingHTTPServer(("127.0.0.1", _free_port()),
                                     lb_handler)
    threading.Thread(target=lb.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{lb.server_address[1]}"
    follower_pids = []
    try:
        assert _wait_code(a + "/health", 200), "gang A never ready"
        assert _wait_code(b + "/health", 200), "replica B never ready"
        follower_pids = [m["pid"] for m in _gang_members(port_a)
                         if m["role"] == "follower"]

        prompt, mt = [1, 2, 3], 12
        greedy = {"prompt": prompt, "max_tokens": mt, "stream": True}
        seeded = dict(greedy, temperature=0.9, seed=21)
        refs = {}
        for name, doc in (("greedy", greedy), ("seeded", seeded)):
            status, body = stream_bytes(b, doc)
            assert status == 200, f"reference {name} failed"
            refs[name] = body
        assert refs["greedy"] != refs["seeded"]

        # Round 1 (greedy): LB-side stream kill via the lb.stream
        # fault point; the splice comes from gang A's peer B.
        before_ok = lb_lib._RESUMES.labels(outcome="ok").get()
        fi.activate("lb.stream", times=1, skip=4)
        try:
            status, body = stream_bytes(base, greedy)
        finally:
            fi.clear()
        assert status == 200
        assert body == refs["greedy"], "greedy splice diverged"

        # Round 2 (seeded): SIGKILL the whole gang A process group
        # mid-stream — the hard preemption. The journal resumes on B.
        result = {}
        sink = []

        def consume():
            result["out"] = stream_bytes(base, seeded, sink=sink)

        client = threading.Thread(target=consume, daemon=True)
        client.start()
        deadline = time.time() + 60
        while time.time() < deadline:
            if b"".join(sink).count(b"data: {") >= 2:
                break
            time.sleep(0.02)
        else:
            pytest.fail("stream never produced tokens via gang A")
        import os
        import signal as signal_lib
        os.killpg(os.getpgid(proc_a.pid), signal_lib.SIGKILL)
        client.join(timeout=120)
        assert "out" in result, "client stream never finished"
        status, body = result["out"]
        assert status == 200
        assert body == refs["seeded"], "post-SIGKILL splice diverged"
        assert lb_lib._RESUMES.labels(
            outcome="ok").get() >= before_ok + 2
    finally:
        fi.clear()
        lb.shutdown()
        import os
        import signal as signal_lib
        for proc in (proc_a, proc_b):
            if proc.poll() is None:
                try:
                    os.killpg(os.getpgid(proc.pid),
                              signal_lib.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait(timeout=10)
        # The gang's self-spawned followers sit in their own sessions;
        # the 2s heartbeat timeout reaps them, but don't leak on a
        # fast exit either.
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and any(
                _pid_alive(p) for p in follower_pids):
            time.sleep(0.2)
        for pid in follower_pids:
            if _pid_alive(pid):
                try:
                    os.kill(pid, signal_lib.SIGKILL)
                except ProcessLookupError:
                    pass
