"""The engine autotuner (skypilot_tpu/tune/): manifest contract,
geometry resolution, parity at non-default constants, handshake drift.

Four layers, cheapest first:

* the manifest SCHEMA is pinned (constants + validate() rejections) so
  the document shape can't drift silently under an unchanged version;
* load/save round-trip, fail-closed fallback on corrupt/stale/
  sha-mismatched files, and the env-var resolution order;
* resolve_kv_geometry's 0-sentinel override policy (manifest fills
  only knobs the caller left unset; explicit args win; the payload-sha
  tag rides the geometry dict, so gang followers with a drifted
  manifest die at join);
* engine-output parity AT tuned constants — the same
  tune.parity.check_parity gate `stpu tune` runs on every winner
  before persisting, here parametrized over families and paged/dense
  at a deliberately non-default tile/chunk.
"""
import json
import socket
import threading

import jax
import pytest

from skypilot_tpu.serve import decode_engine, gang_replica
from skypilot_tpu.serve.decode_engine import DecodeEngine
from skypilot_tpu.tune import manifest as tune_manifest
from skypilot_tpu.tune import sweep as tune_sweep
from skypilot_tpu.tune.parity import check_parity


PROV = {"device_kind": "cpu", "commit": "abc1234",
        "created": "2026-08-06T00:00:00+0000"}


@pytest.fixture
def manifest_env(tmp_state_dir, monkeypatch):
    """Hermetic manifest state: ~/.stpu in a tmpdir, no ambient
    STPU_TUNE_MANIFEST, caches cleared both sides."""
    monkeypatch.delenv("STPU_TUNE_MANIFEST", raising=False)
    tune_manifest.reset_for_tests()
    yield tmp_state_dir
    tune_manifest.reset_for_tests()


def _tiny():
    from skypilot_tpu.models import llama
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    return llama, cfg, llama.init(cfg, jax.random.key(0))


# ==================================================== schema contract
def test_manifest_schema_pinned():
    """The constants the doc shape hangs off: bumping any of these is
    a schema revision and must be a conscious change."""
    assert tune_manifest.SCHEMA_VERSION == 1
    assert tune_manifest.ENTRY_KNOBS == ("block", "chunk",
                                         "window_blocks", "spec_k")
    assert tune_manifest.REQUIRED_PROVENANCE == ("device_kind",
                                                 "commit", "created")
    assert tune_manifest.ENV_MANIFEST == "STPU_TUNE_MANIFEST"


def test_tuning_key_bands_and_quant_modes():
    assert tune_manifest.tuning_key("llama", 2) == "llama|b1-4|tp1|bf16"
    assert tune_manifest.tuning_key(
        "mixtral", 8, tp=4, kv_quant=True,
        weight_quant=True) == "mixtral|b5-16|tp4|q8kvw"
    assert tune_manifest.batch_band(17) == "b17+"
    assert tune_manifest.quant_mode(True, False) == "q8kv"
    assert tune_manifest.quant_mode(False, True) == "q8w"


def _valid_doc(entries=None):
    payload = {"provenance": dict(PROV),
               "entries": entries if entries is not None else {
                   "llama|b1-4|tp1|bf16": {"block": 128,
                                           "parity": "pass"}}}
    return {"schema": tune_manifest.SCHEMA_VERSION,
            "sha256": tune_manifest.payload_sha(payload),
            "payload": payload}


def test_validate_accepts_and_rejects():
    tune_manifest.validate(_valid_doc())

    with pytest.raises(tune_manifest.ManifestError, match="stale"):
        doc = _valid_doc()
        doc["schema"] = 99
        tune_manifest.validate(doc)

    with pytest.raises(tune_manifest.ManifestError, match="sha256"):
        doc = _valid_doc()
        doc["payload"]["entries"]["llama|b1-4|tp1|bf16"]["block"] = 256
        tune_manifest.validate(doc)          # payload edited, sha not

    with pytest.raises(tune_manifest.ManifestError, match="tuning key"):
        tune_manifest.validate(_valid_doc(
            {"llama|bf16": {"block": 128, "parity": "pass"}}))

    with pytest.raises(tune_manifest.ManifestError, match="no tuned"):
        tune_manifest.validate(_valid_doc(
            {"llama|b1-4|tp1|bf16": {"parity": "pass"}}))

    with pytest.raises(tune_manifest.ManifestError, match="int"):
        tune_manifest.validate(_valid_doc(
            {"llama|b1-4|tp1|bf16": {"block": True, "parity": "pass"}}))

    with pytest.raises(tune_manifest.ManifestError,
                       match="out of range"):
        tune_manifest.validate(_valid_doc(
            {"llama|b1-4|tp1|bf16": {"chunk": 0, "parity": "pass"}}))

    # spec_k = 0 is a legal tuned value (drafting off) ...
    tune_manifest.validate(_valid_doc(
        {"llama|b1-4|tp1|bf16": {"spec_k": 0, "parity": "pass"}}))

    with pytest.raises(tune_manifest.ManifestError, match="parity"):
        tune_manifest.validate(_valid_doc(
            {"llama|b1-4|tp1|bf16": {"block": 128}}))

    with pytest.raises(tune_manifest.ManifestError,
                       match="provenance"):
        doc = _valid_doc()
        del doc["payload"]["provenance"]["commit"]
        doc["sha256"] = tune_manifest.payload_sha(doc["payload"])
        tune_manifest.validate(doc)


# ================================================= round-trip + fallback
def test_save_load_entry_for_round_trip(manifest_env):
    entries = {"llama|b1-4|tp1|bf16":
               {"block": 128, "chunk": 32, "parity": "pass"}}
    doc = tune_manifest.save(entries, PROV)
    assert tune_manifest.default_path().is_file()

    payload, tag = tune_manifest.load(tune_manifest.default_path())
    assert payload["entries"] == entries
    assert tag == doc["sha256"][:12]

    # Unset env + file at the default path -> auto-pickup.
    entry, got_tag = tune_manifest.entry_for(family="llama", slots=2)
    assert entry == entries["llama|b1-4|tp1|bf16"]
    assert got_tag == tag
    # A config with no entry: default, same (valid) manifest.
    assert tune_manifest.entry_for(family="gemma", slots=2) == \
        (None, "default")


def test_save_merges_existing_entries(manifest_env):
    tune_manifest.save({"llama|b1-4|tp1|bf16":
                        {"block": 128, "parity": "pass"}}, PROV)
    tune_manifest.save({"gemma|b1-4|tp1|bf16":
                        {"chunk": 32, "parity": "pass"}}, PROV)
    payload, _ = tune_manifest.load(tune_manifest.default_path())
    assert set(payload["entries"]) == {"llama|b1-4|tp1|bf16",
                                       "gemma|b1-4|tp1|bf16"}
    # merge=False replaces.
    tune_manifest.save({"mixtral|b1-4|tp1|bf16":
                        {"spec_k": 2, "parity": "pass"}}, PROV,
                       merge=False)
    payload, _ = tune_manifest.load(tune_manifest.default_path())
    assert set(payload["entries"]) == {"mixtral|b1-4|tp1|bf16"}


def test_resolve_path_env_contract(manifest_env, monkeypatch):
    # Unset + no file -> None (defaults).
    assert tune_manifest.resolve_path() is None
    # "0" disables even when the default file exists.
    tune_manifest.save({"llama|b1-4|tp1|bf16":
                        {"block": 128, "parity": "pass"}}, PROV)
    assert tune_manifest.resolve_path() == tune_manifest.default_path()
    monkeypatch.setenv("STPU_TUNE_MANIFEST", "0")
    assert tune_manifest.resolve_path() is None
    assert tune_manifest.entry_for(family="llama", slots=2) == \
        (None, "default")
    # An explicit path wins over the default location.
    other = manifest_env.parent / "other.json"
    tune_manifest.save({"llama|b1-4|tp1|bf16":
                        {"block": 512, "parity": "pass"}}, PROV,
                       path=other, merge=False)
    monkeypatch.setenv("STPU_TUNE_MANIFEST", str(other))
    entry, _ = tune_manifest.entry_for(family="llama", slots=2)
    assert entry["block"] == 512


@pytest.mark.parametrize("corruption", ["garbage", "sha", "stale"])
def test_corrupt_or_stale_manifest_falls_back(manifest_env, capsys,
                                              corruption):
    """A bad manifest must never keep an engine from serving: one
    stderr warning, then default constants."""
    path = tune_manifest.default_path()
    doc = tune_manifest.save({"llama|b1-4|tp1|bf16":
                              {"block": 128, "chunk": 32,
                               "parity": "pass"}}, PROV)
    if corruption == "garbage":
        path.write_text("{not json")
    elif corruption == "sha":
        doc["payload"]["entries"]["llama|b1-4|tp1|bf16"]["block"] = 16
        path.write_text(json.dumps(doc))     # sha now wrong
    else:
        doc["schema"] = 0                    # stale version
        path.write_text(json.dumps(doc))
    tune_manifest.reset_for_tests()

    assert tune_manifest.entry_for(family="llama", slots=2) == \
        (None, "default")
    assert "ignoring manifest" in capsys.readouterr().err
    # Warn once per path, not per lookup.
    tune_manifest.entry_for(family="llama", slots=2)
    assert capsys.readouterr().err == ""

    # The engine still resolves (default constants) and serves.
    geo = decode_engine.resolve_kv_geometry(slots=2, max_seq=64,
                                            family="llama")
    assert geo["manifest"] == "default"
    assert geo["block"] == 64                # SPLIT_KV_BLOCK clamped


# ====================================== geometry resolution + override
def test_manifest_fills_only_unset_knobs(manifest_env):
    tune_manifest.save(
        {"llama|b1-4|tp1|bf16": {"block": 32, "chunk": 16,
                                 "window_blocks": 2, "spec_k": 2,
                                 "parity": "pass"}}, PROV)
    tag = tune_manifest.entry_for(family="llama", slots=2)[1]

    geo = decode_engine.resolve_kv_geometry(slots=2, max_seq=64,
                                            paged=True, family="llama")
    assert (geo["block"], geo["chunk"], geo["window"],
            geo["spec_k"]) == (32, 16, 32, 2)
    assert geo["manifest"] == tag

    # Explicit knobs win over the manifest; untouched ones still fill.
    geo = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, paged=True, prefill_chunk=8,
        family="llama")
    assert geo["chunk"] == 8
    assert geo["block"] == 32
    # kv_block_tokens is the paged alias for chunk — also explicit.
    geo = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, paged=True, kv_block_tokens=8,
        family="llama")
    assert geo["chunk"] == 8

    # use_manifest=False (bench legs, parity reference engines).
    geo = decode_engine.resolve_kv_geometry(slots=2, max_seq=64,
                                            paged=True, family="llama",
                                            use_manifest=False)
    assert geo["manifest"] == "default"
    assert geo["block"] == 64 and geo["chunk"] == 64

    # No family (legacy callers): no lookup at all.
    geo = decode_engine.resolve_kv_geometry(slots=2, max_seq=64)
    assert geo["manifest"] == "default"


def test_engine_startup_loads_manifest_constants(manifest_env):
    """DecodeEngine resolves the manifest at construction: tuned
    constants land in kv_config() (what /perf surfaces and the gang
    handshake compares) without any per-call plumbing."""
    tune_manifest.save(
        {"llama|b1-4|tp1|bf16": {"block": 32, "chunk": 16,
                                 "parity": "pass"}}, PROV)
    mdl, cfg, params = _tiny()
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64, paged=True)
    kv = eng.kv_config()
    assert kv["block"] == 32 and kv["chunk"] == 16
    assert kv["manifest"] != "default"
    # Same knobs, manifest off: the handshake dicts must differ.
    ref = DecodeEngine(cfg, params, slots=2, max_seq=64, paged=True,
                       use_manifest=False)
    assert ref.kv_config() != kv


def test_follower_with_drifted_manifest_dies_at_join(manifest_env):
    """Tuned geometry rides the gang welcome: a follower that resolved
    a different (or no) manifest must die at join (rc 1), not decode
    with drifted tiles out of lockstep."""
    tune_manifest.save(
        {"llama|b1-4|tp1|bf16": {"block": 32, "chunk": 16,
                                 "parity": "pass"}}, PROV)
    topo = gang_replica.ReplicaTopology(hosts=2)
    leader_kv = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, paged=True, family="llama")
    assert leader_kv["manifest"] != "default"
    leader = gang_replica.GangLeader(topo, port=0, kv_config=leader_kv)
    try:
        sock = socket.create_connection(("127.0.0.1", leader.port),
                                        timeout=5.0)
        wf, rf = sock.makefile("wb"), sock.makefile("rb")
        gang_replica._send_line(wf, {"op": "hello", "rank": 1,
                                     "pid": 1})
        assert json.loads(rf.readline())["kv"] == leader_kv
        sock.close()

        class _StubEngine:
            def start(self):
                return self

            def shutdown(self):
                pass

        rc_box = []

        def follower():
            rc_box.append(gang_replica.follower_serve(
                _StubEngine, topo, f"127.0.0.1:{leader.port}", rank=1,
                kv_config=decode_engine.resolve_kv_geometry(
                    slots=2, max_seq=64, paged=True, family="llama",
                    use_manifest=False)))

        t = threading.Thread(target=follower, daemon=True)
        t.start()
        t.join(timeout=30.0)
        assert rc_box == [1]
    finally:
        leader.shutdown()


# ===================================================== sweep mechanics
def test_candidate_grids_include_defaults():
    for mode in tune_sweep.MODES:
        cands = tune_sweep._candidates(mode)
        assert tune_sweep.DEFAULTS[mode] in cands
        assert len(cands) == len({tuple(sorted(c.items()))
                                  for c in cands})  # no dupes
        axes = tune_sweep.SEARCH_SPACE[mode]
        for cand in cands:
            assert set(cand) == set(axes)


def test_tune_cli_registered():
    from click.testing import CliRunner

    from skypilot_tpu import cli
    result = CliRunner().invoke(cli.cli, ["tune", "--help"])
    assert result.exit_code == 0
    assert "manifest" in result.output


# ============================================ parity at tuned constants
# The same gate `stpu tune` runs per winner, at a deliberately
# non-default geometry (tile 32, chunk 16 — tile boundaries inside
# every prompt). Each case drives greedy AND seeded requests; greedy
# output is additionally checked against the models.decode fixed path.
# llama runs in tier-1 (the shared engine machinery); mixtral/gemma
# recompile the same programs against their own attention variants and
# ride the slow lane with the other long-compile suites.
_FAMILIES = ["llama",
             pytest.param("mixtral", marks=pytest.mark.slow),
             pytest.param("gemma", marks=pytest.mark.slow)]


@pytest.mark.parametrize("family", _FAMILIES)
def test_parity_at_tuned_constants_dense(family):
    check_parity(family, block=32, chunk=16, paged=False,
                 n_requests=2, max_tokens=4)


@pytest.mark.parametrize("family", _FAMILIES)
def test_parity_at_tuned_constants_paged(family):
    check_parity(family, chunk=16, window_blocks=2, paged=True,
                 n_requests=2, max_tokens=4)


def test_parity_gate_catches_a_planted_divergence(monkeypatch):
    """The gate itself must be falsifiable: feed it a reference that
    cannot match and the ParityError must fire (a gate that never
    fails gates nothing)."""
    from skypilot_tpu.tune import parity as parity_mod

    real = parity_mod._drain
    flip = {"n": 0}

    def crooked(engine, specs):
        out = real(engine, specs)
        flip["n"] += 1
        if flip["n"] == 2:                   # corrupt the reference run
            out = [list(s) for s in out]
            out[0][0] = (out[0][0] + 1) % 100
        return out

    monkeypatch.setattr(parity_mod, "_drain", crooked)
    with pytest.raises(parity_mod.ParityError):
        parity_mod.check_parity("llama", block=32, n_requests=1,
                                max_tokens=3)
