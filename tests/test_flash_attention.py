"""Pallas flash attention vs XLA reference (interpret mode on CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops.pallas import flash_attention as fa


def _make_qkv(key, b=2, s=256, h=4, kvh=2, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, h, d), dtype=dtype)
    k = jax.random.normal(kk, (b, s, kvh, d), dtype=dtype)
    v = jax.random.normal(kv, (b, s, kvh, d), dtype=dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = _make_qkv(jax.random.key(0))
    out = fa.flash_attention(q, k, v, causal=causal, block_q=128,
                             block_k=128)
    ref = attention_ops._reference_attention(q, k, v, causal=causal,
                                             scale=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_flash_gradients_match_reference():
    q, k, v = _make_qkv(jax.random.key(1), s=128)

    def loss_flash(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, causal=True,
                                          block_q=64, block_k=64) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_ops._reference_attention(
            q, k, v, causal=True, scale=None) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_flash_irregular_shape_falls_back():
    # seq not divisible by block -> reference fallback, still correct.
    q, k, v = _make_qkv(jax.random.key(2), s=100)
    out = fa.flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = attention_ops._reference_attention(q, k, v, causal=True,
                                             scale=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)


def test_streamed_kernels_match_resident(monkeypatch):
    """Long-context (streamed) kernel family vs the resident-KV family:
    same math, different VMEM strategy — outputs and grads must agree."""
    q, k, v = _make_qkv(jax.random.key(3), s=256)

    def run(use_resident):
        monkeypatch.setattr(fa, "_use_resident",
                            lambda s, d: use_resident)

        def loss(q, k, v):
            return jnp.sum(fa.flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64) ** 2)
        out = fa.flash_attention(q, k, v, causal=True, block_q=64,
                                 block_k=64)
        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return out, grads

    o_r, g_r = run(True)
    o_s, g_s = run(False)
    np.testing.assert_allclose(np.asarray(o_s), np.asarray(o_r),
                               rtol=2e-3, atol=2e-3)
    for a, b in zip(g_s, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_tri_family_unequal_blocks(monkeypatch):
    """Triangular causal family with block_q != block_k (bound / lo
    arithmetic is exercised off the square-block fast path)."""
    q, k, v = _make_qkv(jax.random.key(4), s=256)
    monkeypatch.setattr(fa, "_use_resident", lambda s, d: False)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(
            q, k, v, causal=True, block_q=128, block_k=64) ** 2)

    out = fa.flash_attention(q, k, v, causal=True, block_q=128,
                             block_k=64)
    ref = attention_ops._reference_attention(q, k, v, causal=True,
                                             scale=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    gf = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        attention_ops._reference_attention(q, k, v, causal=True,
                                           scale=None) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)


def test_tri_family_unequal_blocks_kq(monkeypatch):
    """block_k > block_q: diagonal-straddle predicate must still mask,
    fwd AND bwd (the dkv kernel's lo/diag arithmetic runs in the
    wide-KV regime only here)."""
    q, k, v = _make_qkv(jax.random.key(5), s=256)
    monkeypatch.setattr(fa, "_use_resident", lambda s, d: False)
    out = fa.flash_attention(q, k, v, causal=True, block_q=64,
                             block_k=128)
    ref = attention_ops._reference_attention(q, k, v, causal=True,
                                             scale=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-3, atol=2e-3)
    gf = jax.grad(lambda q, k, v: jnp.sum(fa.flash_attention(
        q, k, v, causal=True, block_q=64, block_k=128) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        attention_ops._reference_attention(q, k, v, causal=True,
                                           scale=None) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=5e-3)
