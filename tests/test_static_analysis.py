"""The unified static-analysis framework (`stpu check`).

One tier-1 test replaces the four scattered lint tests
(test_observability / test_fault_tolerance / test_sharded_replica /
test_checkpoint): the whole rule suite runs over ``skypilot_tpu/`` in
one AST walk per file and must be clean. Every rule also gets a
good/bad/noqa'd fixture corpus, the ``--json`` schema is pinned, and
the env-knob table embedded in docs/static-analysis.md is asserted
byte-identical to ``env_contract.render_markdown_table()`` so the doc
can never drift from the registry.
"""
import json
import pathlib
import subprocess
import sys
import textwrap

import pytest
from click.testing import CliRunner

from skypilot_tpu import analysis
from skypilot_tpu.utils import env_contract

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write(tmp_path: pathlib.Path, rel: str, body: str) -> pathlib.Path:
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(body))
    return path


def _run(tmp_path, rule):
    """Run ONE rule over the fixture tree; findings keyed by rel:line."""
    findings = analysis.run_check(paths=[tmp_path], rules=[rule])
    return findings


def _lines(findings, rel):
    return sorted(f.line for f in findings if f.path == rel)


# ================================================= tier-1: repo clean
def test_repo_clean_all_rules():
    """`stpu check` over skypilot_tpu/ is clean across ALL rules —
    including the three TPU-correctness analyzers (donation,
    host-sync, env contract). This is THE lint gate; a finding here is
    a real bug or a site that needs an explained noqa."""
    findings = analysis.run_check()
    assert findings == [], "\n".join(f.render() for f in findings)
    # All seven+ advertised rules actually ran (registry intact).
    ids = {r.id for r in analysis.all_rules()}
    assert {"stpu-wallclock", "stpu-span-leak", "stpu-except",
            "stpu-atomic", "stpu-collective", "stpu-donation",
            "stpu-host-sync", "stpu-env", "stpu-armed-guard"} <= ids


# ================================================= suppression grammar
def test_noqa_reason_mandatory(tmp_path):
    """The unified grammar: `# noqa: stpu-<rule> <reason>` suppresses;
    a marker with no (or a too-short) reason does NOT."""
    _write(tmp_path, "probe.py", """\
        import time
        a = time.time() - t0
        b = time.time() - t1  # noqa: stpu-wallclock
        c = time.time() - t2  # noqa: stpu-wallclock persisted stamp from another boot
        """)
    findings = _run(tmp_path, "stpu-wallclock")
    assert _lines(findings, "probe.py") == [2, 3]
    missing = [f for f in findings if f.line == 3]
    assert "reason is missing" in missing[0].message


def test_noqa_multi_rule(tmp_path):
    """One line can suppress several rules: `# noqa: stpu-a, stpu-b
    <reason>` — and a rule NOT named on the line still fires."""
    _write(tmp_path, "serve/probe.py", """\
        import time
        from jax import lax
        x = lax.psum(time.time() - t0, 'tp')  # noqa: stpu-collective, stpu-wallclock both exercised by this fixture
        y = lax.psum(1, 'tp')  # noqa: stpu-wallclock wrong rule named
        """)
    col = _run(tmp_path, "stpu-collective")
    assert _lines(col, "serve/probe.py") == [4]
    assert _run(tmp_path, "stpu-wallclock") == []


# ================================================= ported rules corpus
def test_wallclock_rule(tmp_path):
    _write(tmp_path, "good.py", """\
        import time
        t0 = time.perf_counter()
        dur = time.perf_counter() - t0
        stamp = time.time()
        """)
    _write(tmp_path, "bad.py", """\
        import time
        dur = time.time() - t0
        """)
    findings = _run(tmp_path, "stpu-wallclock")
    assert _lines(findings, "bad.py") == [2]
    assert _lines(findings, "good.py") == []


def test_span_leak_rule(tmp_path):
    _write(tmp_path, "spans.py", """\
        from skypilot_tpu.observability import tracing
        def good_with():
            with tracing.start_span('a') as s:
                s.event('e')
        def good_assign():
            span = tracing.start_span('b')
            try:
                pass
            finally:
                span.end()
        def good_nested_closer():
            span = tracing.start_span('c')
            def finish():
                span.end(status='ok')
            finish()
        def bad_returned():
            return tracing.start_span('d')
        def bad_dropped():
            tracing.start_span('e')
        def bad_never_ended():
            leak = tracing.start_span('f')
            leak.event('x')
        def noqad():
            return tracing.start_span('g')  # noqa: stpu-span-leak caller owns the end()
        """)
    findings = _run(tmp_path, "stpu-span-leak")
    assert _lines(findings, "spans.py") == [17, 19, 21]


def test_except_rule(tmp_path):
    _write(tmp_path, "serve/bad.py", """\
        try:
            x = 1
        except Exception:
            pass
        try:
            y = 1
        except:
            pass
        try:
            z = 1
        except ValueError:
            pass
        """)
    _write(tmp_path, "serve/ok.py", """\
        try:
            x = 1
        except Exception:  # noqa: stpu-except best-effort probe, failure means no data
            pass
        """)
    _write(tmp_path, "elsewhere/bad.py",
           "try:\n    x = 1\nexcept Exception:\n    pass\n")
    findings = _run(tmp_path, "stpu-except")
    assert _lines(findings, "serve/bad.py") == [3, 7]
    assert _lines(findings, "serve/ok.py") == []
    # Only the control-plane dirs are in scope.
    assert _lines(findings, "elsewhere/bad.py") == []


def test_atomic_rule(tmp_path):
    _write(tmp_path, "train/checkpoint.py", """\
        import os, pathlib
        def write_state(p, q):
            with open(p, "w") as f:
                f.write("x")
            pathlib.Path(q).write_text("y")
            fd = os.open(p, os.O_WRONLY)
            open(p).read()
            with open(p, "rb") as f:
                f.read()
        def atomic_write_bytes(path, data):
            fd = os.open(path, os.O_WRONLY | os.O_CREAT)
            os.write(fd, data)
        def scratch(p):
            open(p, "w").write("tmp")  # noqa: stpu-atomic scratch file, rebuilt on every boot
        """)
    findings = _run(tmp_path, "stpu-atomic")
    assert _lines(findings, "train/checkpoint.py") == [3, 5, 6]


def test_collective_rule(tmp_path):
    _write(tmp_path, "serve/bad.py", """\
        import jax
        def f(x):
            return jax.lax.psum(x, 'tp')
        """)
    _write(tmp_path, "serve/ok.py", """\
        def local(x):
            psum = 3
            return psum
        """)
    _write(tmp_path, "serve/lazy.py", """\
        from jax.lax import psum
        def f(x):
            return psum(x, 'tp')  # noqa: stpu-collective
        """)
    findings = _run(tmp_path, "stpu-collective")
    assert _lines(findings, "serve/bad.py") == [3]
    assert _lines(findings, "serve/ok.py") == []
    lazy = [f for f in findings if f.path == "serve/lazy.py"]
    assert len(lazy) == 1 and "reason is missing" in lazy[0].message


# ================================================= new TPU analyzers
DONATION_FIXTURE = """\
    import functools
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(1,))
    def step(tokens, cache):
        cache = cache.at[0].set(tokens)
        return tokens + 1, cache

    @functools.partial(jax.jit, donate_argnums=(0,))
    def dead_end(cache):
        return jnp.zeros(3)

    def bad_use_after_donate(tokens, cache):
        logits, _ = step(tokens, cache)
        return cache[0]

    def bad_loop_no_rebind(tokens, cache):
        for _ in range(4):
            logits, _ = step(tokens, cache)
        return logits

    def good_rebinds(tokens, cache):
        logits, cache = step(tokens, cache)
        logits, cache = step(logits, cache)
        return logits, cache

    def good_goes_dead(tokens, cache):
        logits, _ = step(tokens, cache)
        return logits

    def noqad(tokens, cache):
        logits, _ = step(tokens, cache)
        return cache[0]  # noqa: stpu-donation CPU-only diagnostic path, never runs on TPU
    """


def test_donation_rule_seeded_fixture(tmp_path):
    """Acceptance: the donation analyzer catches a seeded
    use-after-donate (and the no-output-alias callee trap), while the
    engine's rebind convention passes."""
    _write(tmp_path, "donation.py", DONATION_FIXTURE)
    findings = _run(tmp_path, "stpu-donation")
    lines = _lines(findings, "donation.py")
    # 11: dead_end's donated param aliases no output;
    # 16: read-after-donate; 20: donating call in a loop, no rebind.
    assert lines == [11, 16, 20], [f.render() for f in findings]
    by_line = {f.line: f.message for f in findings}
    assert "aliases no output" in by_line[11]
    assert "read after being donated" in by_line[16]
    assert "inside a loop" in by_line[20]


def test_donation_rule_fresh_buffer_per_iteration(tmp_path):
    """A loop that stores a FRESH buffer before each donating call is
    clean — the back-edge read sees the new buffer, not the donated
    one."""
    _write(tmp_path, "fresh.py", """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(1,))
        def step(b, cache):
            return b, cache

        def per_batch(batches, init_cache):
            for b in batches:
                cache = init_cache(b)
                out, _ = step(b, cache)
            return out
        """)
    assert _run(tmp_path, "stpu-donation") == []


def test_donation_rule_covers_paged_entry_points():
    """The analyzer SEES the paged block-table entry points: both
    _paged_prefill_chunk and _paged_step register as donators with the
    pool (positional index 2) donated — so a future use-after-donate
    of the paged pool fails the gate exactly like the dense cache."""
    from skypilot_tpu.analysis import rules_donation
    src = REPO / "skypilot_tpu" / "serve" / "decode_engine.py"
    ctx = analysis.core.FileContext(src, "serve/decode_engine.py")
    donators = {d.name: d
                for d in rules_donation._collect_donators(ctx)
                if d.name}
    for name in ("_paged_prefill_chunk", "_paged_step",
                 "_prefill_chunk", "_engine_step", "_paged_spec_step"):
        assert name in donators, f"{name} not seen as a donator"
        assert "cache" in donators[name].donated_params(), name


def test_donation_rule_paged_block_table_fixture(tmp_path):
    """The paged calling shape: the pool donated through a block-table
    call with extra (table / static-window) operands. Rebinding from
    the return is clean; reading the pool after donating it — or
    donating in the decode loop without rebind — is flagged. The
    TABLE is not donated, so reading it after the call stays clean."""
    _write(tmp_path, "paged.py", """\
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnums=(0, 4),
                           donate_argnums=(1,))
        def paged_step(cfg, pool, toks, table, window):
            pool = pool.at[table[0]].set(toks)
            return toks + 1, pool

        def good_engine_loop(cfg, pool, toks, table):
            for _ in range(8):
                toks, pool = paged_step(cfg, pool, toks, table, 64)
                probe = table[0]        # table NOT donated: fine
            return toks, pool

        def bad_pool_read(cfg, pool, toks, table):
            nxt, _ = paged_step(cfg, pool, toks, table, 64)
            return pool[0]

        def bad_loop_no_rebind(cfg, pool, toks, table):
            for _ in range(8):
                nxt, _ = paged_step(cfg, pool, toks, table, 64)
            return nxt
        """)
    findings = _run(tmp_path, "stpu-donation")
    lines = _lines(findings, "paged.py")
    assert lines == [19, 23], [f.render() for f in findings]


def test_donation_rule_self_attribute_paths(tmp_path):
    """Dotted donation targets (`self._cache`) are tracked: rebinding
    from the return is clean, a later read is use-after-donate —
    exactly the decode-engine convention."""
    _write(tmp_path, "engine.py", """\
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _engine_step(toks, cache):
            return toks, cache

        class Engine:
            def good(self, toks):
                toks, self._cache = _engine_step(toks, self._cache)
                return toks
            def bad(self, toks):
                toks2, _ = _engine_step(toks, self._cache)
                return self._cache
        """)
    findings = _run(tmp_path, "stpu-donation")
    assert _lines(findings, "engine.py") == [14]


def test_host_sync_rule(tmp_path):
    _write(tmp_path, "serve/decode_engine.py", """\
        import functools
        import jax
        import jax.numpy as jnp
        import numpy as np

        @functools.partial(jax.jit, donate_argnums=(1,))
        def _decode_step(tokens, cache):
            return tokens + 1, cache

        def engine_loop(tokens, cache):
            while True:
                tokens, cache = _decode_step(tokens, cache)
                t = tokens.item()
                host = np.asarray(tokens)
                print(tokens)
                fetched = jax.device_get(tokens)
                ok = float(fetched[0])
                temp = float("0.7")

        def hot_helper(tokens):
            val = jnp.sum(tokens)
            return float(val)

        def cold_helper(request):
            return float(request["temperature"])
        """)
    findings = _run(tmp_path, "stpu-host-sync")
    lines = _lines(findings, "serve/decode_engine.py")
    # .item(), np.asarray(device), print(device) flagged; the
    # device_get fetch un-taints, so the post-fetch float() and host
    # scalars (cold_helper's temperature) never trip the rule.
    assert 13 in lines and 14 in lines and 15 in lines
    assert 17 not in lines and 18 not in lines and 25 not in lines
    # Reachability scope: hot_helper is never called from the per-token
    # path, so its float(jnp.sum(...)) is out of scope by design.
    assert 22 not in lines
    # The rule only targets the two engine files: same sync pattern in
    # another serve/ module is out of scope.
    _write(tmp_path, "serve/other.py", "def f(a):\n    return a.item()\n")
    findings = _run(tmp_path, "stpu-host-sync")
    assert _lines(findings, "serve/other.py") == []


def test_host_sync_sanctioned_sampled_sync(tmp_path):
    """stepstats.sampled_sync is THE blessed sync seam on the serve
    hot path: never flagged, while every other block_until_ready
    spelling (method form AND jax.block_until_ready call form) is."""
    _write(tmp_path, "serve/decode_engine.py", """\
        import jax
        from skypilot_tpu.observability import stepstats

        @jax.jit
        def _engine_step(tokens, cache):
            return tokens + 1, cache

        def engine_loop(tokens, cache):
            while True:
                tokens, cache = _engine_step(tokens, cache)
                if stepstats.ENABLED and stepstats.sync_due():
                    device_s = stepstats.sampled_sync(tokens)
                jax.block_until_ready(tokens)
                tokens.block_until_ready()
        """)
    findings = _run(tmp_path, "stpu-host-sync")
    lines = _lines(findings, "serve/decode_engine.py")
    # The sanctioned helper (line 12) passes; both raw sync spellings
    # (13: call form, 14: method form) are findings.
    assert 12 not in lines
    assert 13 in lines and 14 in lines
    by_line = {f.line: f.message for f in findings}
    assert "sampled_sync" in by_line[13]


def test_host_sync_noqa(tmp_path):
    _write(tmp_path, "serve/gang_replica.py", """\
        def broadcast_generate(arr):
            arr.block_until_ready()  # noqa: stpu-host-sync gang barrier needs a hard sync point
            return arr.item()
        """)
    findings = _run(tmp_path, "stpu-host-sync")
    assert _lines(findings, "serve/gang_replica.py") == [3]


def test_host_sync_train_loop_bad_fixture(tmp_path):
    """The rule now targets the train loops: a recipe loop that
    float()s its loss every step, .item()s a metric, or hard-syncs
    with block_until_ready is flagged like the decode engine."""
    _write(tmp_path, "recipes/llama_lora.py", """\
        import jax

        @jax.jit
        def step_fn(state, batch):
            return state, batch.sum()

        def run(state, batches):
            for batch in batches:
                state, loss = step_fn(state, batch)
                log = float(loss)
                item = loss.item()
                loss.block_until_ready()
        """)
    findings = _run(tmp_path, "stpu-host-sync")
    assert _lines(findings, "recipes/llama_lora.py") == [10, 11, 12]


def test_host_sync_train_loop_good_fixture(tmp_path):
    """The sanctioned train-loop pattern passes clean: DelayedFetch
    rotation + the literal jax.device_get of the PREVIOUS handle, and
    trainstats.sampled_sync as the only in-loop device sync."""
    _write(tmp_path, "recipes/llama_lora.py", """\
        import jax
        from skypilot_tpu.observability import trainstats
        from skypilot_tpu.train import trainer

        @jax.jit
        def step_fn(state, batch):
            return state, batch.sum()

        def run(state, batches):
            delayed = trainer.DelayedFetch()
            for batch in batches:
                state, loss = step_fn(state, batch)
                prev = delayed.rotate(loss)
                if prev is not None:
                    host_loss = jax.device_get(prev)
                    fetched = float(host_loss)
                if trainstats.ENABLED and trainstats.sync_due():
                    device_s = trainstats.sampled_sync(loss)
        """)
    findings = _run(tmp_path, "stpu-host-sync")
    assert _lines(findings, "recipes/llama_lora.py") == []


def test_host_sync_jit_factory_taints_train_loop(tmp_path):
    """`step = trainer.make_train_step(...)` is a jitted entry point
    (_JIT_FACTORIES) even with no local @jax.jit — the loop calling it
    is hot and a per-step float(metrics) there is a finding."""
    _write(tmp_path, "recipes/mixtral_ep.py", """\
        from skypilot_tpu.train import trainer

        def run(state, batches, tx, mesh, rules):
            step = trainer.make_train_step(lambda p, t, c: t, tx,
                                           mesh, rules)
            for batch in batches:
                state, metrics = step(state, batch)
                loss = float(metrics["loss"])
        """)
    findings = _run(tmp_path, "stpu-host-sync")
    assert _lines(findings, "recipes/mixtral_ep.py") == [8]
    # The same loop in a NON-target file stays out of scope.
    _write(tmp_path, "recipes/other_recipe.py", """\
        from skypilot_tpu.train import trainer

        def run(state, batches, tx, mesh, rules):
            step = trainer.make_train_step(lambda p, t, c: t, tx,
                                           mesh, rules)
            for batch in batches:
                state, metrics = step(state, batch)
                loss = float(metrics["loss"])
        """)
    findings = _run(tmp_path, "stpu-host-sync")
    assert _lines(findings, "recipes/other_recipe.py") == []


def test_armed_guard_rule(tmp_path):
    """The good/bad/noqa trio for stpu-armed-guard: unguarded
    observability calls on a hot module are findings; flag guards
    (plain, compound, alias, elif, in-test), armed-only helpers, the
    sanctioned no-op callees, and explained noqas all pass."""
    _write(tmp_path, "serve/decode_engine.py", """\
        from skypilot_tpu.observability import reqlog, stepstats, tracing
        from skypilot_tpu.utils import fault_injection

        def bad_step(live):
            stepstats.record(live=len(live))
            fault_injection.fire("engine.step")

        def good_plain(live):
            if stepstats.ENABLED:
                stepstats.record(live=len(live))

        def good_compound(stats):
            if reqlog.ENABLED and stats.get("reqlog") is not None:
                reqlog.write_record(stats["reqlog"])

        def good_alias(live):
            armed = stepstats.ENABLED
            if armed and live:
                stepstats.record(live=len(live))

        def good_in_test():
            if stepstats.ENABLED and stepstats.sync_due():
                pass

        def good_elif(x):
            if x:
                pass
            elif reqlog.ENABLED and x is None:
                reqlog.mint_id()

        def _record_helper(i):
            stepstats.record_admission(i)

        def caller(i):
            if stepstats.ENABLED:
                _record_helper(i)

        def good_sanctioned(headers):
            return tracing.extract(headers)

        def noqad():
            stepstats.record(x=1)  # noqa: stpu-armed-guard one-shot startup probe, never per-token

        def bad_disarmed_branch():
            if stepstats.ENABLED:
                pass
            else:
                stepstats.record(x=1)
        """)
    findings = _run(tmp_path, "stpu-armed-guard")
    lines = _lines(findings, "serve/decode_engine.py")
    assert lines == [5, 6, 48]
    assert "stepstats.ENABLED" in {f.line: f.message
                                   for f in findings}[5]


def test_armed_guard_unguarded_helper_is_flagged(tmp_path):
    """A helper whose call sites do NOT all guard gets no armed-only
    credit — the call inside it is a finding."""
    _write(tmp_path, "serve/load_balancer.py", """\
        from skypilot_tpu.observability import reqlog

        def helper(rec):
            reqlog.write_record(rec)

        def guarded_caller(rec):
            if reqlog.ENABLED:
                helper(rec)

        def unguarded_caller(rec):
            helper(rec)
        """)
    findings = _run(tmp_path, "stpu-armed-guard")
    assert _lines(findings, "serve/load_balancer.py") == [4]


def test_armed_guard_targets_hot_modules_only(tmp_path):
    """Cold control-plane code is out of scope: the same unguarded
    call in a non-target file is never flagged."""
    _write(tmp_path, "serve/controller.py", """\
        from skypilot_tpu.observability import stepstats

        def f():
            stepstats.record(x=1)
        """)
    findings = _run(tmp_path, "stpu-armed-guard")
    assert _lines(findings, "serve/controller.py") == []


def test_env_rule_seeded_fixture(tmp_path):
    """Acceptance: an unregistered STPU_* read fails; a default
    literal that disagrees with env_contract.py fails; registered
    reads with the registered default pass."""
    _write(tmp_path, "env_probe.py", """\
        import os
        A = os.environ.get("STPU_NOT_A_REAL_KNOB", "1")
        B = os.environ.get("STPU_ENGINE_SLOTS", "8")
        C = os.environ["STPU_ALSO_NOT_REAL"]
        D = os.environ.get("STPU_ENGINE_SLOTS", "4")
        E = os.environ.get("STPU_LB_POLICY")
        F = os.getenv("STPU_THIRD_FAKE")
        G = os.environ.get("HOME", "/root")
        H = os.environ.get("STPU_GRANDFATHERED", "x")  # noqa: stpu-env migration shim removed next release
        I = os.environ.get("STPU_DISABLE_EVENTS")
        """)
    findings = _run(tmp_path, "stpu-env")
    lines = _lines(findings, "env_probe.py")
    # Line 10: a presence-style read (no inline default) of a
    # defaulted knob is NOT a disagreement — only inline literals are.
    assert lines == [2, 3, 4, 7]
    by_line = {f.line: f.message for f in findings}
    assert "not registered" in by_line[2]
    assert "registers '4'" in by_line[3]


def test_env_rule_resolves_constants(tmp_path):
    """Reads through module constants resolve: locally
    (`ENABLE_ENV = "STPU_TRACE"`), and cross-file for dotted reads
    (`tracing.ENV_CTX`). Ambiguous bare names never resolve."""
    _write(tmp_path, "tracing.py", """\
        import os
        ENABLE_ENV = "STPU_TRACE"
        FAKE_ENV = "STPU_CONSTANT_FAKE"
        armed = os.environ.get(ENABLE_ENV, "0") == "1"
        bad = os.environ.get(FAKE_ENV)
        """)
    _write(tmp_path, "consumer.py", """\
        import os
        from . import tracing
        ctx = os.environ.get(tracing.FAKE_ENV)
        """)
    findings = _run(tmp_path, "stpu-env")
    assert _lines(findings, "tracing.py") == [5]
    assert _lines(findings, "consumer.py") == [3]


def test_env_registry_covers_repo_reads():
    """Every STPU_* env read in skypilot_tpu/ resolves through
    env_contract.py (the repo-wide clean run enforces it; this pins
    the rule actually VISITED the tree by checking a known knob)."""
    findings = analysis.run_check(rules=["stpu-env"])
    assert findings == [], "\n".join(f.render() for f in findings)
    assert "STPU_ENGINE_SLOTS" in env_contract.REGISTRY
    assert env_contract.REGISTRY["STPU_HOME"].default == "~/.stpu"


def test_unparsable_file_is_a_finding(tmp_path):
    """A file that fails ast.parse must FAIL the gate (stpu-parse), not
    silently pass every AST rule."""
    _write(tmp_path, "train/checkpoint.py", """\
        def write_state(p):
        <<<<<<< merge conflict
            open(p, "w").write("x")
        """)
    findings = analysis.run_check(paths=[tmp_path])
    parse = [f for f in findings if f.rule == "stpu-parse"]
    assert len(parse) == 1 and parse[0].path == "train/checkpoint.py"
    assert "syntax error" in parse[0].message


def test_targets_are_path_bounded(tmp_path):
    """Suffix matching is '/'-bounded: restrain/checkpoint.py is not
    train/checkpoint.py, observe/decode_engine.py is not the engine."""
    body = 'f = open("x", "w")\n'
    _write(tmp_path, "restrain/checkpoint.py", body)
    _write(tmp_path, "train/checkpoint.py", body)
    findings = _run(tmp_path, "stpu-atomic")
    assert _lines(findings, "train/checkpoint.py") == [1]
    assert _lines(findings, "restrain/checkpoint.py") == []
    _write(tmp_path, "observe/decode_engine.py",
           "def f(a):\n    return a.item()\n")
    findings = _run(tmp_path, "stpu-host-sync")
    assert _lines(findings, "observe/decode_engine.py") == []


def test_atomic_shim_lints_explicit_paths(tmp_path):
    """Historical API: tools/check_atomic_writes.check([paths]) lints
    exactly the files it is given, whatever they are named."""
    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_atomic_writes
        bad = _write(tmp_path, "some_state_writer.py",
                     'f = open("x", "w")\n')
        violations = check_atomic_writes.check([bad])
        assert len(violations) == 1 and "stpu-atomic" in violations[0]
    finally:
        sys.path.pop(0)


# ================================================= CLI + json schema
def test_cli_check_clean_and_json(tmp_path):
    from skypilot_tpu import cli
    runner = CliRunner()
    bad = _write(tmp_path, "bad.py",
                 "import time\nd = time.time() - t0\n")
    result = runner.invoke(cli.cli, ["check", str(bad)])
    assert result.exit_code == 1
    assert "bad.py:2:stpu-wallclock:" in result.output

    result = runner.invoke(cli.cli, ["check", "--json", str(bad)])
    assert result.exit_code == 1
    payload = json.loads(result.output)
    assert isinstance(payload, list) and payload
    # Pinned schema: exactly these keys.
    assert set(payload[0]) == {"path", "line", "rule", "message"}
    assert payload[0]["rule"] == "stpu-wallclock"
    assert payload[0]["line"] == 2

    good = _write(tmp_path, "good.py", "x = 1\n")
    result = runner.invoke(cli.cli, ["check", str(good)])
    assert result.exit_code == 0, result.output
    result = runner.invoke(cli.cli, ["check", "--json", str(good)])
    assert json.loads(result.output) == []


def test_cli_check_rule_selection(tmp_path):
    from skypilot_tpu import cli
    runner = CliRunner()
    bad = _write(tmp_path, "bad.py",
                 "import time\nd = time.time() - t0\n")
    result = runner.invoke(
        cli.cli, ["check", "--rule", "stpu-donation", str(bad)])
    assert result.exit_code == 0, result.output
    result = runner.invoke(
        cli.cli, ["check", "--rule", "stpu-nonsense", str(bad)])
    assert result.exit_code != 0
    assert "unknown rule" in result.output

    result = runner.invoke(cli.cli, ["check", "--list-rules"])
    assert result.exit_code == 0
    assert "stpu-donation" in result.output
    assert "stpu-env" in result.output


def test_cli_check_repo_default_clean():
    """`stpu check` with no PATHS scans skypilot_tpu/ and exits 0."""
    from skypilot_tpu import cli
    runner = CliRunner()
    result = runner.invoke(cli.cli, ["check"])
    assert result.exit_code == 0, result.output
    assert "0 finding(s)" in result.output


# ================================================= tools/ shims
def test_tools_shims_still_work():
    """`python tools/check_*.py` invocations keep working (exit 0 on
    the clean repo, framework-rendered output)."""
    for script in ("check_clocks.py", "check_excepts.py",
                   "check_collectives.py", "check_atomic_writes.py"):
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / script)],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, (script, proc.stdout, proc.stderr)
        assert "OK" in proc.stdout


# ================================================= env-table doc sync
def test_env_table_doc_in_sync():
    """docs/static-analysis.md embeds `stpu check --env-table` output
    between markers; it must be byte-identical to the registry render
    so the doc can never drift from code."""
    doc = (REPO / "docs" / "static-analysis.md").read_text()
    begin = "<!-- env-table:begin (stpu check --env-table) -->"
    end = "<!-- env-table:end -->"
    assert begin in doc and end in doc
    embedded = doc.split(begin, 1)[1].split(end, 1)[0].strip()
    assert embedded == env_contract.render_markdown_table(), (
        "docs/static-analysis.md env table is stale — regenerate with "
        "`stpu check --env-table`")


def test_cli_env_table_matches_registry():
    from skypilot_tpu import cli
    runner = CliRunner()
    result = runner.invoke(cli.cli, ["check", "--env-table"])
    assert result.exit_code == 0
    assert result.output.strip() == env_contract.render_markdown_table()
    # Every registered knob appears exactly once.
    for name in env_contract.REGISTRY:
        assert f"`{name}`" in result.output


def test_registry_rejects_bad_knobs():
    with pytest.raises(ValueError):
        env_contract._k("NOT_STPU", None, "doc")
    with pytest.raises(ValueError):
        env_contract._k("STPU_X", None, "   ")
