"""Fleet telemetry store + SLO burn monitor + latency autoscaling e2e.

The acceptance story (ISSUE 14): a loadgen run drives a stub serving
stack whose LB-side TTFB lands in the controller-resident
TimeSeriesStore via the fleet collector; the store's p99 matches the
loadgen client's within one histogram bucket; an injected
``lb.upstream`` delay fault trips the fast burn window → ``slo_breach``
event → the ``scaling_policy: latency`` autoscaler scales up → after
recovery both windows clear → ``slo_recovered`` → scale back down —
all asserted through the real ``GET /fleet`` path (controller sync
server, forwarded by the LB) and the ``stpu top`` / ``stpu slo`` CLI.

Plus the pins: the collector's scrape→record→doc contract against
canned endpoints, monitor rebuild on spec swap, the satellite-3 CLI
guarantee (None renders as ``-``, never ``nan``), and the disarmed
zero-overhead contract (STPU_FLEET=0 constructs nothing — enforced
with monkeypatch bombs on every constructor the armed path uses).
"""
import bisect
import http.server
import json
import socket
import socketserver
import threading
import time
from types import SimpleNamespace

import pytest

from skypilot_tpu.benchmark import loadgen
from skypilot_tpu.observability import events
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability.timeseries import TimeSeriesStore
from skypilot_tpu.serve import autoscalers
from skypilot_tpu.serve import fleet
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve.load_balancing_policies import RoundRobinPolicy
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.utils import fault_injection as fi


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        pass


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start(handler_cls):
    server = _Server(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def _canned(routes):
    """HTTP server answering GET from a {path: body-or-callable} map."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = routes.get(self.path)
            if body is None:
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            data = body() if callable(body) else body
            if isinstance(data, str):
                data = data.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    return _start(Handler)


# ====================================================== collector unit
def _fake_controller(spec=None):
    spec = spec or SkyServiceSpec(min_replicas=1)
    return SimpleNamespace(
        service_name="svc", spec=spec, _ready_urls=[], fleet=None,
        autoscaler=autoscalers.Autoscaler.from_spec(spec))


def test_collector_scrape_record_and_doc():
    """One collect tick pulls every allowlisted replica family plus
    the LB edge families into the store; doc() is the JSON-safe live
    view over them, with a dead replica degrading to None fields."""
    reg = metrics.Registry()
    slots = reg.gauge("stpu_engine_slots_occupied")
    slots.set(3)
    reg.gauge("stpu_engine_slots_total").set(8)
    reg.gauge("stpu_engine_queue_depth").set(2)
    reg.gauge("stpu_engine_kv_pool_blocks_free").set(10)
    reg.gauge("stpu_engine_kv_pool_blocks_total").set(16)
    decode_total = reg.counter("stpu_engine_decode_tokens_total")
    decode_total.inc(100)
    ttft = reg.histogram("stpu_engine_ttft_seconds", buckets=(0.1, 1.0))
    ttft.observe(0.05)
    step = reg.histogram("stpu_engine_step_seconds", "", ("phase",),
                         buckets=(0.1, 1.0))
    step.labels(phase="decode").observe(0.01)
    perf = {"armed": True, "busy_fraction": 0.5,
            "tokens_per_sec": {"prefill": 10.0, "decode": 200.0}}
    replica_srv, replica_url = _canned({
        "/metrics": reg.render,
        "/perf": lambda: json.dumps(perf)})

    lbreg = metrics.Registry()
    ttfb = lbreg.histogram("stpu_lb_ttfb_seconds", buckets=(0.1, 1.0))
    ttfb.observe(0.05)
    requests = lbreg.counter("stpu_lb_requests_total", "", ("code",))
    requests.labels(code="200").inc(5)
    requests.labels(code="502").inc(1)
    lb_srv, lb_url = _canned({"/metrics": lbreg.render})

    dead_url = f"http://127.0.0.1:{_free_port()}"
    controller = _fake_controller()
    controller._ready_urls = [replica_url]
    store = TimeSeriesStore(raw_seconds=1.0, raw_retention=10000.0)
    collector = fleet.FleetCollector(controller, lb_url, interval=5.0,
                                     store=store)
    try:
        collector.collect_once(now=100.0)
        assert store.latest("stpu_engine_slots_occupied",
                            replica=replica_url) == 3.0
        assert store.latest("stpu_engine_decode_tokens_total",
                            replica=replica_url) == 100.0
        assert store.latest("stpu_perf_busy_fraction",
                            replica=replica_url) == 0.5
        assert store.latest("stpu_perf_tokens_per_sec",
                            replica=replica_url, phase="decode") == 200.0
        assert store.latest("stpu_lb_requests_total", code="200") == 5.0

        # The world moves on; the dead replica joins the ready set.
        slots.set(4)
        decode_total.inc(60)
        ttft.observe(0.05)
        ttft.observe(0.5)
        perf["busy_fraction"] = 0.7
        perf["tokens_per_sec"]["decode"] = 250.0
        for v in (0.05, 0.05, 0.5):
            ttfb.observe(v)
        requests.labels(code="200").inc(3)
        requests.labels(code="502").inc(1)
        controller._ready_urls = [replica_url, dead_url]
        collector.collect_once(now=130.0)
    finally:
        replica_srv.shutdown()
        lb_srv.shutdown()

    doc = collector.doc(now=130.0)
    assert doc["service"] == "svc"
    assert doc["collected_at"] == 130.0
    live = doc["replicas"][replica_url]
    assert live["busy_fraction"] == 0.7
    assert live["slots"] == {"occupied": 4.0, "total": 8.0}
    assert live["tokens_per_sec"]["decode"] == 250.0
    # Counter-derived decode rate: 60 new tokens over the live window.
    assert live["decode_tokens_per_sec"] == pytest.approx(
        60.0 / doc["window_s"])
    assert live["ttft"]["count"] == 2
    # The dead replica contributed no points: every field None, and
    # the doc still JSON-serializes (sanitized, no NaN leakage).
    dead = doc["replicas"][dead_url]
    assert dead["busy_fraction"] is None and dead["ttft"] is None
    json.dumps(doc)
    assert doc["lb"]["ttfb"]["count"] == 3
    assert doc["lb"]["request_rate"] == pytest.approx(
        4.0 / doc["window_s"])
    assert doc["slo"] is None                 # no objectives declared
    assert doc["autoscaler"]["policy"] == "Autoscaler"
    assert doc["autoscaler"]["target"] == 1
    assert "stpu_lb_requests_total" in doc["series_names"]
    with_series = collector.doc(series="stpu_perf_busy_fraction",
                                now=130.0)
    assert with_series["series_data"]["series"] == \
        "stpu_perf_busy_fraction"
    assert with_series["series_data"]["data"]


def test_collector_rebuilds_monitor_on_spec_swap():
    """`serve update` swaps controller.spec wholesale — the collector
    rebuilds the monitor on identity change and keeps it otherwise
    (breach edges must not reset every tick)."""
    controller = _fake_controller()
    store = TimeSeriesStore(raw_seconds=1.0, raw_retention=1000.0)
    collector = fleet.FleetCollector(controller, "", interval=1.0,
                                     store=store)
    collector.collect_once(now=10.0)
    assert collector.monitor is None
    controller.spec = SkyServiceSpec(
        min_replicas=1,
        slo_objectives=({"kind": "error_rate", "target": 0.99},))
    collector.collect_once(now=20.0)
    assert collector.monitor is not None
    assert collector.monitor.objectives[0].kind == "error_rate"
    monitor = collector.monitor
    collector.collect_once(now=30.0)
    assert collector.monitor is monitor


# ============================================ satellite 3: '-' not nan
_CANNED_DOC = {
    "service": "render-svc",
    "collected_at": None,
    "interval_s": 10.0,
    "window_s": 300.0,
    "replicas": {
        "http://10.0.0.1:9009": {
            "busy_fraction": None,
            "tokens_per_sec": {"prefill": None, "decode": None},
            "decode_tokens_per_sec": None,
            "slots": {"occupied": None, "total": None},
            "kv_pool": {"free": None, "total": None},
            "queue_depth": None,
            "ttft": None,
        }},
    "lb": {"ttfb": None, "request_rate": None},
    "slo": {"service": "render-svc", "fast_window_s": 300.0,
            "slow_window_s": 3600.0, "burn_threshold": 1.0,
            "degraded": False,
            "objectives": [{"kind": "ttft", "target": 0.99,
                            "threshold_seconds": 1.0,
                            "burn_fast": None, "burn_slow": None,
                            "budget_remaining": None,
                            "breaching": False}]},
    "autoscaler": {"policy": "LatencyAwareAutoscaler", "target": 1,
                   "qps": None, "last_decision": None},
    "series_names": [],
}


def test_cli_top_and_slo_render_missing_data_as_dash(monkeypatch):
    """An idle fleet (empty histogram windows → None readings) renders
    as '-' in `stpu top`/`stpu slo` — never 'nan' or a crash."""
    from click.testing import CliRunner

    from skypilot_tpu import core
    from skypilot_tpu.cli import cli
    monkeypatch.setattr(
        core, "fleet_snapshot",
        lambda url, series=None, since=None: dict(_CANNED_DOC))
    runner = CliRunner()
    res = runner.invoke(cli, ["top", "--url", "http://fake"])
    assert res.exit_code == 0, res.output
    assert "render-svc" in res.output
    assert "collected never" in res.output
    assert "p50 -" in res.output and "rate -/s" in res.output
    assert "-/-" in res.output                # tok/s, slots, pool cells
    assert "(qps -)" in res.output
    assert "nan" not in res.output.lower()
    assert "None" not in res.output
    assert "BREACHING" not in res.output and "DEGRADED" not in res.output

    res = runner.invoke(cli, ["slo", "--url", "http://fake"])
    assert res.exit_code == 0, res.output
    assert "ttft" in res.output and "ok" in res.output
    assert "nan" not in res.output.lower()
    assert "BREACHING" not in res.output


# ======================================= disarmed: zero-overhead pins
def test_fleet_disarmed_constructs_nothing(monkeypatch):
    """STPU_FLEET=0: maybe_start returns None without touching ANY of
    the armed path's constructors — store, monitor, collector."""
    assert fleet.enabled()                    # armed by default

    def boom(*a, **kw):
        raise AssertionError("constructed despite STPU_FLEET=0")

    monkeypatch.setenv("STPU_FLEET", "0")
    monkeypatch.setattr(fleet, "FleetCollector", boom)
    monkeypatch.setattr(fleet, "store_from_env", boom)
    monkeypatch.setattr(fleet.timeseries, "TimeSeriesStore", boom)
    monkeypatch.setattr(fleet.slo_lib, "SloMonitor", boom)
    controller = SimpleNamespace(fleet=None)
    assert fleet.maybe_start(controller, "http://127.0.0.1:1") is None
    assert controller.fleet is None


# ================================================================= e2e
class _StubReplica(http.server.BaseHTTPRequestHandler):
    """Stub serving replica: SSE token stream with a pre-headers
    'prefill' delay (so LB TTFB and client TTFT share a dominant
    constant), /metrics from the process registry, /perf armed."""
    protocol_version = "HTTP/1.1"
    headers_delay = 0.12
    delay = 0.002
    token_cap = 4

    def log_message(self, *a):
        pass

    def do_GET(self):
        if self.path == "/perf":
            body = json.dumps(
                {"armed": True, "steps": 4, "busy_fraction": 0.25,
                 "tokens_per_sec": {"prefill": 0.0,
                                    "decode": 50.0}}).encode()
        elif self.path == "/metrics":
            body = metrics.render().encode()
        else:
            body = b"{}"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        from skypilot_tpu.serve import decode_engine
        length = int(self.headers.get("Content-Length") or 0)
        req = json.loads(self.rfile.read(length) or b"{}")
        time.sleep(self.headers_delay)
        t0 = time.perf_counter()
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        n = min(int(req.get("max_tokens", 4)), self.token_cap)
        for i in range(n):
            time.sleep(self.delay)
            if i == 0:
                decode_engine._TTFT.observe(time.perf_counter() - t0)
            lb_lib.write_chunk(
                self.wfile, f'data: {{"token": {i}}}\n\n'.encode())
        lb_lib.write_chunk(self.wfile, b"data: [DONE]\n\n")
        lb_lib.end_chunks(self.wfile)


def _start_lb(policy, **handler_attrs):
    port = _free_port()
    handler = type("Handler", (lb_lib._ProxyHandler,), {
        "policy": policy, "recorder": lb_lib.RequestRecorder(),
        "breaker": lb_lib.CircuitBreaker(), **handler_attrs})
    server = lb_lib._ThreadingHTTPServer(("127.0.0.1", port), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{port}"


def _bucket_idx(v: float) -> int:
    return bisect.bisect_left(list(metrics.LATENCY_BUCKETS), v)


def test_fleet_e2e_breach_scales_up_and_recovers(tmp_state_dir,
                                                 tmp_path, monkeypatch):
    """The acceptance e2e. Timeline (controlled collector timestamps,
    live scrape content):

      t0       baseline collect (zero-delta windows)
      t0+10    clean loadgen → store p99 ≈ client p99 (±1 bucket)
      t0+40    faulted loadgen (lb.upstream delay 0.8s > 0.5s SLO
               threshold) → fast AND slow burn → slo_breach →
               latency policy scales 1→2
      t0+100   clean loadgen → both windows clean → slo_recovered →
               scales 2→1
    """
    from click.testing import CliRunner

    from skypilot_tpu import core
    from skypilot_tpu.cli import cli
    from skypilot_tpu.serve.controller import SkyServeController
    monkeypatch.setenv("STPU_SLO_FAST_WINDOW", "30")
    monkeypatch.setenv("STPU_SLO_SLOW_WINDOW", "60")
    service = "fleet-e2e"
    replica, replica_url = _start(
        type("R", (_StubReplica,), {}))
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([replica_url])
    spec = SkyServiceSpec(
        min_replicas=1, max_replicas=3, target_qps_per_replica=100.0,
        qps_window_seconds=10, upscale_delay_seconds=0,
        downscale_delay_seconds=0, scaling_policy="latency",
        slo_objectives=(
            {"kind": "ttft", "target": 0.9, "threshold_seconds": 0.5},
            {"kind": "error_rate", "target": 0.9}))
    controller = SkyServeController(
        service, spec, task=SimpleNamespace(uses_spot=False))
    controller._ready_urls = [replica_url]
    sync_port = controller.start_sync_server()
    lb, target = _start_lb(
        policy, controller_url=f"http://127.0.0.1:{sync_port}")
    scaler = controller.autoscaler
    assert type(scaler) is autoscalers.LatencyAwareAutoscaler
    runner = CliRunner()

    # Before the collector attaches, /fleet (forwarded by the LB from
    # the controller sync server) is a clean error, not a crash.
    res = runner.invoke(cli, ["top", "--url", target])
    assert res.exit_code != 0

    store = TimeSeriesStore(raw_seconds=1.0, raw_retention=10000.0)
    collector = fleet.FleetCollector(controller, target, interval=0.25,
                                     store=store)
    controller.fleet = collector   # manual ticks: deterministic tests
    t0 = 1000.0
    try:
        # -------------------------------------------------- baseline
        collector.collect_once(now=t0)
        signals = scaler._latency_signals
        assert signals["degraded"] is False
        assert signals["ttft"]["burn_fast"] is None   # empty, not NaN

        # ---------------------------------------------- clean traffic
        clean = loadgen.run(
            target,
            loadgen.LoadSpec(mix="chat", qps=10, duration_s=1.5,
                             seed=11, max_tokens=4),
            slo_ttft_s=1.0, scrape_interval=0.6,
            out_dir=str(tmp_path / "clean"))
        assert clean["requests"]["ok"] > 0
        collector.collect_once(now=t0 + 10)
        snap = store.histogram_delta("stpu_lb_ttfb_seconds",
                                     window=30.0, now=t0 + 10)
        assert snap is not None and snap.count >= clean["requests"]["ok"]
        # The tentpole accuracy claim: the store's service-edge p99
        # lands within one LATENCY_BUCKETS bucket of the loadgen
        # client's own measurement.
        client_p99 = clean["latency_s"]["ttft"]["p99"]
        store_p99 = snap.quantile(0.99)
        assert abs(_bucket_idx(store_p99) - _bucket_idx(client_p99)) \
            <= 1, (store_p99, client_p99)
        signals = scaler._latency_signals
        assert signals["ttft"]["burn_fast"] == 0.0    # all under 0.5s
        assert signals["degraded"] is False
        assert scaler.plan(now=t0 + 10, num_ready=1).total == 1

        # ------------------------------------------------ fault phase
        loadgen.run(
            target,
            loadgen.LoadSpec(mix="chat", qps=8, duration_s=1.2,
                             seed=4, max_tokens=4),
            slo_ttft_s=0.5, scrape_interval=0.6,
            out_dir=str(tmp_path / "slow"),
            faults="lb.upstream:delay:s=0.8", faults_at=0.0)
        assert not fi.ENABLED
        collector.collect_once(now=t0 + 40)
        signals = scaler._latency_signals
        # Fast window saw only faulted traffic: 100% bad, burn ==
        # 1.0 / (1 - 0.9) == 10; slow window mixes clean + faulted but
        # still burns over threshold.
        assert signals["ttft"]["burn_fast"] == pytest.approx(10.0)
        assert signals["ttft"]["burn_slow"] >= 1.0
        assert signals["ttft"]["breaching"] is True
        assert signals["degraded"] is True
        recs = events.read(kind="slo", name=service)
        assert [r["event"] for r in recs] == ["slo_breach"]
        assert recs[0]["objective"] == "ttft"
        # Latency policy: QPS alone says 1 replica; burn scales to 2.
        assert scaler.plan(now=t0 + 40, num_ready=1).total == 2

        # Asserted through the REAL path: GET /fleet on the service
        # endpoint (LB → controller sync server → collector.doc()).
        doc = core.fleet_snapshot(target)
        assert doc["service"] == service
        assert doc["slo"]["degraded"] is True
        by_kind = {o["kind"]: o for o in doc["slo"]["objectives"]}
        assert by_kind["ttft"]["breaching"] is True
        assert by_kind["error_rate"]["breaching"] is False  # all 200s
        assert doc["autoscaler"]["policy"] == "LatencyAwareAutoscaler"
        assert doc["autoscaler"]["target"] == 2
        assert replica_url in doc["replicas"]

        res = runner.invoke(cli, ["top", "--url", target])
        assert res.exit_code == 0, res.output
        assert service in res.output
        assert "BREACHING" in res.output
        assert "DEGRADED" in res.output
        assert "nan" not in res.output.lower()
        res = runner.invoke(cli, ["slo", "--url", target])
        assert res.exit_code == 0, res.output
        assert "BREACHING" in res.output
        assert "10.00" in res.output          # the fast burn, rendered

        # --------------------------------------------------- recovery
        loadgen.run(
            target,
            loadgen.LoadSpec(mix="chat", qps=10, duration_s=1.5,
                             seed=21, max_tokens=4),
            slo_ttft_s=1.0, scrape_interval=0.6,
            out_dir=str(tmp_path / "recovered"))
        collector.collect_once(now=t0 + 100)
        signals = scaler._latency_signals
        assert signals["ttft"]["burn_fast"] == 0.0
        assert signals["degraded"] is False
        recs = events.read(kind="slo", name=service)
        assert [r["event"] for r in recs] == ["slo_breach",
                                              "slo_recovered"]
        # Burn cleared in BOTH windows: the downscale veto lifts and
        # the QPS baseline takes the fleet back to 1.
        assert scaler.plan(now=t0 + 100, num_ready=2).total == 1

        res = runner.invoke(cli, ["top", "--url", target])
        assert res.exit_code == 0, res.output
        assert "DEGRADED" not in res.output
        assert "BREACHING" not in res.output
    finally:
        lb.shutdown()
        replica.shutdown()
        controller._sync_server.shutdown()
