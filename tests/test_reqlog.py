"""Request analytics: wide-event records, tail-biased sampling, and
the capture→replay bridge into loadgen.

ISSUE 20 acceptance pinned here:
  * one streamed request through a real LB + replica + engine writes
    ONE joined JSONL record (LB half + engine half folded from the
    trailing ``stats`` SSE frame, which the client never sees);
  * at ``STPU_REQLOG_SAMPLE=0.01`` an injected error and an injected
    slow request BOTH still produce records (the tail is never
    sampled away);
  * disarmed, the LB proxy path and the engine submit path never
    reach the reqlog module past the ENABLED flag (monkeypatch-bomb
    pinned, mirror of the tracing/fault-injection guarantee);
  * capture → ``derive_spec`` → replay is deterministic (identical
    schedule digest across two derivations from the same records) and
    the replayed run reproduces the source run's prefix-cache hit
    rate within ±10% absolute.
"""
import dataclasses
import json
import socket
import threading
import time
import urllib.request

import pytest
from click.testing import CliRunner

from skypilot_tpu.observability import reqlog, tracing


@pytest.fixture
def rl_armed(tmp_state_dir):
    reqlog.arm(sample=1.0)
    yield tmp_state_dir
    reqlog.disarm()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _tiny_llm():
    import jax

    from skypilot_tpu.models import llama
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    return cfg, params


# ------------------------------------------------------- sampling unit
def test_disarmed_writes_nothing(tmp_state_dir):
    assert not reqlog.ENABLED
    assert reqlog.write_record(
        {"request_id": reqlog.mint_id(), "status": "200"}) is False
    import pathlib
    assert not pathlib.Path(reqlog.requests_path()).exists()


def test_mint_id_shape():
    a, b = reqlog.mint_id(), reqlog.mint_id()
    assert a != b
    for rid in (a, b):
        assert len(rid) == 32
        assert int(rid, 16) >= 0    # pure hex, trace-id compatible


def test_keep_reason_contract(rl_armed):
    reqlog.arm(sample=1.0, slow_ttft=1.0, slow_e2e=10.0)
    ok = {"status": "200", "ttft_s": 0.05, "e2e_s": 0.5}
    assert reqlog.keep_reason(ok) is None
    assert reqlog.keep_reason({"status": "500"}) == "error"
    assert reqlog.keep_reason({"status": "upstream_aborted"}) == "error"
    assert reqlog.keep_reason(
        {"status": "200", "error": "boom"}) == "error"
    assert reqlog.keep_reason(
        {"status": "200", "resumed": True}) == "resumed"
    assert reqlog.keep_reason(
        {"status": "200", "ttft_s": 2.0}) == "slow_ttft"
    assert reqlog.keep_reason(
        {"status": "200", "ttft_s": 0.1, "e2e_s": 20.0}) == "slow_e2e"
    # error outranks slow: a failed request is kept as an error.
    assert reqlog.keep_reason({"status": "503", "ttft_s": 5.0}) == \
        "error"
    assert reqlog.is_slow({"ttft_s": 2.0})
    assert reqlog.is_slow({"e2e_s": 11.0})
    assert not reqlog.is_slow(ok)


def test_tail_biased_sampling_keeps_errors_and_slow(rl_armed):
    """The acceptance pin: at sample=0.01 plain successes are thinned
    but an injected error, an injected slow request, and a resumed
    stream ALWAYS land — tails are the point of a request log."""
    reqlog.arm(sample=0.01, slow_ttft=1.0, slow_e2e=10.0)
    kept = sum(
        1 for _ in range(300)
        if reqlog.write_record({"request_id": reqlog.mint_id(),
                                "status": "200", "ttft_s": 0.01,
                                "e2e_s": 0.05}))
    # P(>=30 keeps | n=300, p=0.01) is astronomically small.
    assert kept < 30
    err = {"request_id": reqlog.mint_id(), "status": "500"}
    slow = {"request_id": reqlog.mint_id(), "status": "200",
            "ttft_s": 5.0}
    resumed = {"request_id": reqlog.mint_id(), "status": "200",
               "ttft_s": 0.01, "resumed": True}
    assert reqlog.write_record(err) is True
    assert reqlog.write_record(slow) is True
    assert reqlog.write_record(resumed) is True
    assert err["keep"] == "error"
    assert slow["keep"] == "slow_ttft"
    assert resumed["keep"] == "resumed"
    recs = reqlog.read()
    by_id = {r["request_id"]: r for r in recs}
    assert by_id[err["request_id"]]["keep"] == "error"
    assert by_id[slow["request_id"]]["keep"] == "slow_ttft"
    assert by_id[resumed["request_id"]]["keep"] == "resumed"
    # Uniform-sample keeps carry NO keep marker (they are the
    # baseline, not a biased keep).
    assert all("keep" not in r for r in recs
               if r["status"] == "200" and not r.get("resumed")
               and not reqlog.is_slow(r))


def test_read_by_id_prefix(rl_armed):
    a = {"request_id": "aa" * 16, "status": "200"}
    b = {"request_id": "ab" * 16, "status": "200"}
    reqlog.write_record(a)
    reqlog.write_record(b)
    assert [r["request_id"] for r in reqlog.read(request_id="aa")] == \
        ["aa" * 16]
    # A shared prefix returns both — the CLI turns that into an
    # "ambiguous id" error.
    assert len(reqlog.read(request_id="a")) == 2
    assert reqlog.read(request_id="ff") == []


# ----------------------------------------------------------- e2e joined
@pytest.mark.usefixtures("tmp_state_dir")
def test_reqlog_e2e_joined_record():
    """One streamed request through real LB + replica + engine: the
    client sees tokens and [DONE] (never the stats frame); the log
    gets ONE joined record with both halves. A non-streamed request
    degrades to an LB-only record — engine halves ride SSE."""
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import \
        RoundRobinPolicy

    assert not tracing.ENABLED       # reqlog arms INDEPENDENTLY
    reqlog.arm(sample=1.0)
    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=300)
    replica = f"http://127.0.0.1:{httpd.server_address[1]}"
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([replica])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    lb_url = f"http://127.0.0.1:{lb.server_address[1]}"

    def generate(payload):
        req = urllib.request.Request(
            lb_url + "/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, resp.read()

    try:
        status, body = generate({"prompt": [1, 2, 3], "max_tokens": 4,
                                 "stream": True})
        assert status == 200
        assert b"[DONE]" in body
        assert body.count(b'"token"') == 4
        # The engine half must NOT leak into the client stream.
        assert b"event: stats" not in body
        assert b"queue_wait_s" not in body

        rec = None
        deadline = time.time() + 20
        while time.time() < deadline:
            recs = [r for r in reqlog.read()
                    if r.get("path") == "/generate"
                    and r.get("stream")]
            if recs and "engine" in recs[0]:
                rec = recs[0]
                break
            time.sleep(0.05)
        assert rec is not None, "joined record never landed"

        # LB half.
        assert len(rec["request_id"]) == 32
        assert rec["method"] == "POST"
        assert rec["status"] == "200"
        assert rec["replica"] == replica
        assert rec["policy"] == "RoundRobinPolicy"
        assert rec["attempts"] == 1 and rec["retries"] == 0
        assert rec["resumed"] is False
        assert rec["trace_sampled"] is False     # tracing stayed off
        assert rec["prompt_tokens"] == 3
        assert rec["max_tokens"] == 4
        assert rec["stream"] is True
        assert len(rec["prefix_hash"]) == 16
        assert rec["e2e_s"] > 0
        assert rec["ttft_s"] is not None and rec["ttft_s"] >= 0
        assert rec["bytes_streamed"] > 0
        assert "keep" not in rec                 # plain success

        # Engine half (folded from the stripped stats frame).
        eng = rec["engine"]
        assert eng["prompt_tokens"] == 3
        assert eng["generated_tokens"] == 4
        assert eng["queue_wait_s"] is not None
        assert eng["device_time_s"] > 0
        assert eng["ttft_s"] is not None
        assert eng["outcome"] == "ok" and eng["error"] is None
        assert isinstance(eng["kv_paged"], bool)
        assert eng["restarts"] == 0

        # Non-streamed: the JSON response path has no SSE frame to
        # ride — the record degrades to LB-only, exactly like a
        # legacy replica.
        n_before = len(reqlog.read())
        status, body = generate({"prompt": [4, 5], "max_tokens": 2})
        assert status == 200
        assert len(json.loads(body)["tokens"]) == 2
        plain = None
        deadline = time.time() + 20
        while time.time() < deadline:
            recs = reqlog.read()
            if len(recs) > n_before:
                plain = [r for r in recs[n_before:]
                         if r.get("path") == "/generate"][0]
                break
            time.sleep(0.05)
        assert plain is not None
        assert plain["status"] == "200"
        assert plain["prompt_tokens"] == 2
        assert "engine" not in plain

        # The LB's admin surface: GET /requests serves the records so
        # `stpu requests SERVICE` works without shell access.
        with urllib.request.urlopen(lb_url + "/requests?limit=5",
                                    timeout=30) as resp:
            assert resp.status == 200
            served = json.loads(resp.read())
        assert {r["request_id"] for r in served} >= {
            rec["request_id"], plain["request_id"]}
    finally:
        reqlog.disarm()
        lb.shutdown()
        httpd.engine.shutdown()
        httpd.shutdown()


# ------------------------------------------------------ overhead guard
@pytest.mark.usefixtures("tmp_state_dir")
def test_reqlog_disarmed_zero_cost(monkeypatch):
    """With reqlog disarmed, the full LB proxy path and the engine
    submit/prefill/decode/free path never reach the reqlog module past
    the ENABLED flag — any mint/classify/write trips the bomb."""
    import http.server
    import socketserver

    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.decode_engine import DecodeEngine
    from skypilot_tpu.serve.load_balancing_policies import \
        RoundRobinPolicy

    assert not reqlog.ENABLED

    def bomb(*args, **kwargs):
        raise AssertionError(
            "reqlog reached while disarmed (hot path must guard on "
            "reqlog.ENABLED)")

    monkeypatch.setattr(reqlog, "write_record", bomb)
    monkeypatch.setattr(reqlog, "mint_id", bomb)
    monkeypatch.setattr(reqlog, "keep_reason", bomb)

    class _Ok(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class _Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    upstream = _Srv(("127.0.0.1", 0), _Ok)
    threading.Thread(target=upstream.serve_forever,
                     daemon=True).start()
    policy = RoundRobinPolicy()
    policy.set_ready_replicas(
        [f"http://127.0.0.1:{upstream.server_address[1]}"])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    try:
        url = f"http://127.0.0.1:{lb.server_address[1]}/x"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
    finally:
        lb.shutdown()
        upstream.shutdown()

    # Engine path: admission, chunked prefill, decode steps, slot free.
    cfg, params = _tiny_llm()
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8).start()
    try:
        toks = engine.submit([1, 2, 3], max_tokens=4).result(
            timeout=600)
        assert len(toks) == 4
    finally:
        engine.shutdown()


def test_jitted_steps_are_reqlog_free():
    """The jitted/batched compute functions — the per-token hot path —
    carry NO reqlog code even armed: the engine half is assembled at
    slot free, and the device-time share is accumulated host-side in
    the (unjitted) step driver under a guard."""
    import inspect

    from skypilot_tpu.serve import decode_engine
    for fn in (decode_engine._engine_step, decode_engine._spec_step,
               decode_engine._paged_step,
               decode_engine._paged_spec_step,
               decode_engine._prefill_chunk,
               decode_engine._paged_prefill_chunk,
               decode_engine._sample, decode_engine._sample_multi):
        assert "reqlog" not in inspect.getsource(fn), fn.__name__


@pytest.mark.slow
@pytest.mark.usefixtures("tmp_state_dir")
def test_engine_throughput_reqlog_armed_within_noise():
    """Armed reqlog costs one dict build per REQUEST (at slot free)
    plus one float add per step — decode throughput must stay within
    noise of the disarmed engine (generous CPU-CI bound)."""
    from skypilot_tpu.serve.decode_engine import DecodeEngine

    cfg, params = _tiny_llm()

    def run():
        engine = DecodeEngine(cfg, params, slots=4, max_seq=96,
                              prefill_chunk=16).start()
        try:
            engine.warmup()
            t0 = time.perf_counter()
            reqs = [engine.submit([1 + i, 2, 3, 4], max_tokens=24)
                    for i in range(8)]
            total = sum(len(r.result(timeout=600)) for r in reqs)
            return total / (time.perf_counter() - t0)
        finally:
            engine.shutdown()

    cold = run()                   # warm the jit caches once, discard
    del cold
    unarmed = run()
    reqlog.arm(sample=1.0)
    try:
        armed = run()
    finally:
        reqlog.disarm()
    assert armed >= 0.5 * unarmed, (armed, unarmed)


# ------------------------------------------------- capture→replay e2e
@pytest.mark.usefixtures("tmp_state_dir")
def test_capture_derive_replay_reproduces_hit_rate(tmp_path):
    """The acceptance story: drive a real paged LB + engine with
    loadgen, capture the wide-event records, derive a spec, and replay
    the derived schedule against the SAME stack. Derivation is
    deterministic (identical digest twice, order-insensitive) and the
    replay reproduces the source run's prefix-cache hit rate within
    ±10% absolute."""
    from skypilot_tpu.benchmark import loadgen
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import \
        RoundRobinPolicy

    reqlog.arm(sample=1.0)
    cfg, params = _tiny_llm()
    ready = threading.Event()
    # One slot serializes admission: cold misses per prefix are
    # deterministic (exactly one), so the hit-rate comparison isn't
    # noised by concurrent same-prefix admissions racing the trie.
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=1, kv_paged=True)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=300)
    policy = RoundRobinPolicy()
    policy.set_ready_replicas(
        [f"http://127.0.0.1:{httpd.server_address[1]}"])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    lb_url = f"http://127.0.0.1:{lb.server_address[1]}"

    def hit_rate(records):
        halves = [r["engine"] for r in records if r.get("engine")]
        prompt = sum(h.get("prompt_tokens") or 0 for h in halves)
        cached = sum(h.get("cached_prompt_tokens") or 0
                     for h in halves)
        assert prompt > 0
        return cached / prompt

    try:
        # Source run: real traffic with prefix-reuse structure.
        src_spec = loadgen.LoadSpec(
            mix="chat", arrival="poisson", qps=12.0, duration_s=2.0,
            seed=3, n_prefixes=2, prompt_tokens=96, max_tokens=4,
            temperature=0.0, vocab=100)
        src_report = loadgen.run(
            lb_url, src_spec, out_dir=str(tmp_path / "src"),
            scrape_interval=1.0)
        assert src_report["source"] == "spec"
        assert src_report["requests"]["error"] == 0, src_report

        captured = [r for r in reqlog.read()
                    if r.get("path") == "/generate"]
        assert len(captured) >= 10
        n_before = len(reqlog.read())

        # Deterministic derivation: same records, any order →
        # identical spec → bit-identical schedule digest.
        d1 = loadgen.derive_spec(captured)
        d2 = loadgen.derive_spec(list(reversed(captured)))
        assert d1 == d2
        dig1 = loadgen.schedule_digest(loadgen.build_schedule(d1))
        dig2 = loadgen.schedule_digest(loadgen.build_schedule(d2))
        assert dig1 == dig2
        assert d1.mix == "chat"
        assert d1.n_prefixes == 2        # prefix structure recovered
        assert d1.max_tokens == 4

        # Replay: the records never carry prompt text, so prompts are
        # SYNTHESIZED — vocab is harness shaping (tiny model), pinned
        # AFTER the determinism assertions above.
        replay_spec = dataclasses.replace(d1, vocab=100)
        schedule = loadgen.build_schedule(replay_spec)
        sched_path = str(tmp_path / "schedule.json")
        digest = loadgen.save_schedule(sched_path, replay_spec,
                                       schedule)
        report = loadgen.run(
            lb_url, None, schedule_file=sched_path,
            out_dir=str(tmp_path / "replay"), scrape_interval=1.0)
        assert report["source"] == "schedule"
        assert report["schedule_sha256"] == digest
        assert report["requests"]["error"] == 0, report
        # Open-loop integrity surfaced either way.
        assert report["driver"]["lag_p99_s"] is not None

        replayed = [r for r in reqlog.read()[n_before:]
                    if r.get("path") == "/generate"]
        assert len(replayed) >= 10
        src_hit = hit_rate(captured)
        replay_hit = hit_rate(replayed)
        assert src_hit > 0           # the paged trie actually hit
        assert abs(src_hit - replay_hit) <= 0.10, \
            (src_hit, replay_hit)
    finally:
        reqlog.disarm()
        lb.shutdown()
        httpd.engine.shutdown()
        httpd.shutdown()


# --------------------------------------------------------- CLI surface
def test_cli_requests_and_capture(rl_armed, tmp_path):
    """`stpu requests` / `stpu requests show` / `stpu loadgen capture`
    over synthetic records: table + detail rendering, filters, and a
    derived schedule whose digest verifies on reload."""
    from skypilot_tpu import cli as cli_mod
    from skypilot_tpu.benchmark import loadgen

    base = 1700000000.0
    ids = []
    for i in range(24):
        rid = f"{i:02x}" * 16
        ids.append(rid)
        rec = {
            "request_id": rid, "ts": base + i * 0.25,
            "method": "POST", "path": "/generate",
            "trace_sampled": False, "replica": "http://r1:9000",
            "policy": "RoundRobinPolicy", "attempts": 1, "retries": 0,
            "resumed": False, "status": "200",
            "ttft_s": 0.02, "e2e_s": 0.3, "bytes_streamed": 512,
            "prompt_tokens": 80 + (i % 5), "max_tokens": 8,
            "temperature": 0.0, "stream": True,
            "prefix_hash": ("aa" * 8 if i % 2 else "bb" * 8),
        }
        if i == 3:
            rec["status"] = "503"
            rec["engine"] = {"queue_wait_s": 0.001,
                             "prompt_tokens": 83,
                             "cached_prompt_tokens": 64,
                             "generated_tokens": 8,
                             "outcome": "error", "error": "boom"}
        if i == 5:
            rec["ttft_s"] = 3.0
        assert reqlog.write_record(rec)

    runner = CliRunner()
    result = runner.invoke(cli_mod.cli, ["requests", "--limit", "50"])
    assert result.exit_code == 0, result.output
    assert ids[0][:8] in result.output
    assert "REQUEST" in result.output and "TTFT" in result.output
    assert "error" in result.output        # keep column for the 503

    result = runner.invoke(cli_mod.cli,
                           ["requests", "--status", "503"])
    assert result.exit_code == 0, result.output
    assert ids[3][:8] in result.output
    assert ids[4][:8] not in result.output

    result = runner.invoke(cli_mod.cli, ["requests", "--slow"])
    assert result.exit_code == 0, result.output
    assert ids[5][:8] in result.output
    assert ids[4][:8] not in result.output

    result = runner.invoke(cli_mod.cli, ["requests", "--json",
                                         "--limit", "50"])
    assert result.exit_code == 0, result.output
    parsed = [json.loads(line)
              for line in result.output.splitlines() if line]
    assert len(parsed) == 24                 # JSONL, one per record

    # Detail view: engine sub-block when joined, degradation note
    # when LB-only.
    result = runner.invoke(cli_mod.cli,
                           ["requests", "show", ids[3][:10]])
    assert result.exit_code == 0, result.output
    assert "engine" in result.output
    assert "queue_wait_s" in result.output
    result = runner.invoke(cli_mod.cli,
                           ["requests", "show", ids[4][:10]])
    assert result.exit_code == 0, result.output
    assert "LB-only" in result.output

    # capture → schedule.json: digest echoed, reload verifies, and a
    # second derivation pins the identical digest.
    out = str(tmp_path / "schedule.json")
    result = runner.invoke(cli_mod.cli, [
        "loadgen", "capture",
        "--from", str(reqlog.requests_path()), "--out", out])
    assert result.exit_code == 0, result.output
    spec, schedule, digest = loadgen.load_schedule(out)
    assert digest[:12] in result.output
    assert spec.n_prefixes == 2
    out2 = str(tmp_path / "schedule2.json")
    result = runner.invoke(cli_mod.cli, [
        "loadgen", "capture",
        "--from", str(reqlog.requests_path()), "--out", out2])
    assert result.exit_code == 0, result.output
    assert loadgen.load_schedule(out2)[2] == digest
