"""Cloud capability layer: feature tables, backend/optimizer routing.

Reference analog: tests for CloudImplementationFeatures /
check_features_are_supported (sky/clouds/cloud.py:27,524).
"""
import pytest

from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import exceptions
from skypilot_tpu.clouds import CloudImplementationFeatures as F
from skypilot_tpu.resources import Resources


def test_registry_and_unknown_cloud():
    assert clouds_lib.registered_names() == ["docker", "gcp",
                                             "kubernetes", "local"]
    assert clouds_lib.get_cloud("gcp").NAME == "gcp"
    with pytest.raises(exceptions.SkyTpuError, match="Unknown cloud"):
        clouds_lib.get_cloud("aws")
    with pytest.raises(exceptions.InvalidTaskError, match="Unknown cloud"):
        Resources(cloud="aws")


def test_pod_slices_cannot_stop_or_autostop():
    gcp = clouds_lib.get_cloud("gcp")
    pod = Resources(accelerator="tpu-v5p-64")
    single = Resources(accelerator="tpu-v5e-8")
    assert not gcp.supports(pod, F.STOP)
    assert not gcp.supports(pod, F.AUTOSTOP)
    assert gcp.supports(single, F.STOP)
    with pytest.raises(exceptions.NotSupportedError, match="terminate"):
        gcp.check_features_are_supported(pod, [F.STOP])
    # Pods can still autostop --down (terminate path needs no STOP).
    gcp.check_features_are_supported(pod, [F.SPOT_INSTANCE, F.MULTI_NODE])


def test_gcp_feature_table():
    gcp = clouds_lib.get_cloud("gcp")
    res = Resources(accelerator="tpu-v5e-8")
    assert gcp.supports(res, F.SPOT_INSTANCE)
    assert gcp.supports(res, F.MULTI_NODE)
    # r5: firewall management landed (provision/gcp.py open_ports).
    assert gcp.supports(res, F.OPEN_PORTS)
    assert not gcp.supports(res, F.IMAGE_ID)


@pytest.mark.usefixtures("tmp_state_dir")
def test_optimizer_drops_unsupported_feature_candidates():
    from skypilot_tpu import optimizer as optimizer_lib
    from skypilot_tpu.task import Task

    # image_id on GCP: unsupported -> no candidates survive. (ports
    # stopped being a drop reason in r5: open_ports landed.)
    from skypilot_tpu.utils import dag_utils
    task = Task("t", run="true")
    task.set_resources(Resources(accelerator="tpu-v5e-8",
                                 image_id="projects/x/images/y"))
    assert optimizer_lib.launchable_candidates(task) == []
    with pytest.raises(exceptions.ResourcesUnavailableError):
        optimizer_lib.Optimizer.optimize(
            dag_utils.convert_entrypoint_to_dag(task))

    # Ports-requesting tasks now get GCP placements (VERDICT r4 #1
    # done-bar: "optimizer stops filtering ports-requesting tasks off
    # GCP").
    task2 = Task("t2", run="true")
    task2.set_resources(Resources(accelerator="tpu-v5e-8",
                                  ports=(8080,)))
    assert optimizer_lib.launchable_candidates(task2)


@pytest.mark.usefixtures("tmp_state_dir")
def test_optimizer_respects_enabled_clouds():
    from skypilot_tpu import global_user_state
    from skypilot_tpu import optimizer as optimizer_lib
    from skypilot_tpu.task import Task

    task = Task("t", run="true")
    task.set_resources(Resources(accelerator="tpu-v5e-8"))
    # No check ever ran: all clouds planable.
    assert optimizer_lib.launchable_candidates(task)
    # Only 'local' enabled: gcp candidates disappear.
    global_user_state.set_enabled_clouds(["local"])
    assert optimizer_lib.launchable_candidates(task) == []
    global_user_state.set_enabled_clouds(["local", "gcp"])
    assert optimizer_lib.launchable_candidates(task)


@pytest.mark.usefixtures("tmp_state_dir")
def test_backend_autostop_refuses_pod_stop():
    from skypilot_tpu import execution
    from skypilot_tpu.backends import slice_backend
    from skypilot_tpu.task import Task

    task = Task("cap", run="true")
    task.set_resources(Resources(cloud="local"))
    _, handle = execution.launch(task, cluster_name="t-cap",
                                 detach_run=True, stream_logs=False)
    handle.launched_resources = Resources(accelerator="tpu-v5p-64")
    backend = slice_backend.SliceBackend()
    with pytest.raises(exceptions.NotSupportedError, match="terminate"):
        backend.set_autostop(handle, 5, down=False)
    # --down path is allowed for pods.
    backend.set_autostop(handle, 5, down=True)
