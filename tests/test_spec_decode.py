"""Speculative decoding on the engine hot path — self-speculative
n-gram drafts with multi-token paged verification.

The contract under test, strongest first:

  * speculative output is BIT-IDENTICAL to non-speculative decode —
    greedy AND seeded sampling, all three families, dense and paged
    caches (targets are re-sampled with the engine's own
    fold_in(seed, pos) keys, so rejection sampling against the
    deterministic n-gram draft degenerates to exact-match acceptance
    and the stream can never change, only its wall clock);
  * rejected-suffix rollback is safe: dense rows past the accepted
    frontier stay masked, the paged path truncates the grown
    block-table tail back into the pool (reservation returned), and a
    verify window clamped near a request's token budget never writes
    where it could corrupt valid rows;
  * the TP-sharded engine drafts/accepts identically to the
    single-device one, and the same admission sequence reproduces the
    same block tables under speculation (the gang lockstep property);
  * cancel-mid-verify releases every pool reference; an injected
    ``engine.verify`` fault rides the EngineSupervisor restart ladder;
  * acceptance telemetry reaches /metrics, stepstats and /perf, and
    the STPU_SPEC_* knobs are registered in the env contract and the
    gang kv-handshake geometry.
"""
import dataclasses
import random
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import gemma, llama, mixtral
from skypilot_tpu.serve import decode_engine
from skypilot_tpu.serve import gang_replica
from skypilot_tpu.serve.decode_engine import DecodeEngine, EngineError
from skypilot_tpu.utils import fault_injection


def _tiny(family="llama"):
    if family == "mixtral":
        return mixtral, mixtral.MixtralConfig.tiny()
    if family == "gemma":
        return gemma, gemma.GemmaConfig.tiny(vocab_size=128)
    return llama, llama.LlamaConfig.tiny(vocab_size=128)


def _drive(engine, rounds=400):
    """Step an UNSTARTED engine deterministically until idle."""
    for _ in range(rounds):
        engine._admit()
        did = engine._prefill_one()
        did = engine._decode_step() or did
        if not did and not engine._waiting:
            return
    raise AssertionError("engine did not quiesce")


def _mixed_specs(cfg, seed=0, n=3):
    """Ragged mix plus a repetitive prompt that guarantees drafting."""
    rng = random.Random(seed)
    specs = [([rng.randint(1, cfg.vocab_size - 1)
               for _ in range(rng.randint(2, 19))],
              rng.randint(1, 8)) for _ in range(n)]
    specs.append(([5, 6, 7] * 6, 10))
    return specs


# =========================================== bit-identity: all families
@pytest.mark.parametrize("family", ["llama", "mixtral", "gemma"])
def test_spec_greedy_bit_identical_dense_and_paged(family):
    """Greedy speculative streams equal the non-speculative engine's
    token-for-token (itself pinned against the fixed-path decode by
    test_decode_engine/test_paged_kv), dense and paged, with real
    drafting exercised (the repetitive prompt forces verify steps; the
    ragged ones force rejections)."""
    mdl, cfg = _tiny(family)
    params = mdl.init(cfg, jax.random.key(0))
    specs = _mixed_specs(cfg)

    def run(paged, spec_k):
        eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                           prefill_chunk=8, paged=paged,
                           spec_k=spec_k, spec_ngram=2).start()
        try:
            reqs = [eng.submit(p, max_tokens=mt) for p, mt in specs]
            return ([r.result(timeout=300.0) for r in reqs],
                    sum(r.spec_drafted for r in reqs))
        finally:
            eng.shutdown()

    base, zero = run(False, 0)
    assert zero == 0
    dense, drafted_dense = run(False, 4)
    paged, drafted_paged = run(True, 4)
    assert dense == base
    assert paged == base
    assert drafted_dense > 0 and drafted_paged > 0


def test_spec_seeded_sampling_parity():
    """temperature > 0 streams are bit-identical with speculation on:
    the verify targets are sampled with the SAME fold_in(seed, pos)
    keys the 1-token step folds, so acceptance is exact-match and the
    distribution is preserved trivially — the output IS the
    non-speculative sample stream."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    # Near-greedy temperatures settle into draftable cycles (both
    # accepts and rejections fire — probed offline); the hot one
    # exercises pure sampling parity even when nothing drafts.
    specs = [([5, 6, 7] * 6, 14, 0.2, 17),
             ([9, 9, 9, 9, 9, 9, 9, 9], 14, 0.3, 4),
             ([1, 2, 3, 4, 5], 8, 1.1, 123)]

    def run(paged, spec_k):
        eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                           prefill_chunk=8, paged=paged,
                           spec_k=spec_k, spec_ngram=2).start()
        try:
            reqs = [eng.submit(p, max_tokens=mt, temperature=t,
                               seed=s) for p, mt, t, s in specs]
            return ([r.result(timeout=300.0) for r in reqs],
                    sum(r.spec_drafted for r in reqs))
        finally:
            eng.shutdown()

    base, _ = run(False, 0)
    dense, d1 = run(False, 4)
    paged, d2 = run(True, 4)
    assert dense == base and paged == base
    assert d1 > 0 and d2 > 0


def test_spec_window_clamped_near_token_budget_and_row_end():
    """A request one token from its budget must not draft (k clamps to
    remaining - 1), and a long prompt decoding up to the row end still
    streams bit-identically — the verify window's out-of-bounds writes
    are DROPPED, never clamped onto valid rows (a clamped
    dynamic_update_slice would smear draft K/V over the prompt)."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    prompt = [3, 4] * 27                      # 54 tokens, max_seq 64
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, spec_k=4, spec_ngram=2)
    one = eng.submit(prompt, max_tokens=1)    # remaining - 1 == 0
    long = eng.submit(prompt[:-1] + [9], max_tokens=9)
    _drive(eng)
    assert one.result(timeout=5.0)
    assert one.spec_drafted == 0
    got = long.result(timeout=5.0)
    assert long.spec_drafted > 0              # windows reached the end
    ref_eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                           prefill_chunk=8)
    ref = ref_eng.submit(prompt[:-1] + [9], max_tokens=9)
    _drive(ref_eng)
    assert got == ref.result(timeout=5.0)


# ==================================================== TP + determinism
def test_spec_tp_paged_engine_bit_identical_to_dense_single():
    """The TP-sharded speculative paged engine reproduces the
    single-process non-speculative dense engine bit-identically in
    f32 — speculation composes with the full sharded serving path."""
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=128),
                              dtype=jnp.float32)
    params = llama.init(cfg, jax.random.key(0))
    topo = gang_replica.ReplicaTopology(hosts=1, ici_axes={"tp": 2})
    mesh, rules = gang_replica.build_mesh(topo)
    sparams = gang_replica.shard_params(cfg, params, mesh, rules)
    reqs = [([5, 6, 7] * 6, 10, 0.0, 0),
            ([7, 9, 11], 8, 0.8, 123),
            ([4] * 70, 6, 0.0, 0),            # chunked prefill path
            ([9] * 8, 12, 0.7, 7)]

    def run(engine):
        out, drafted = [], 0
        try:
            handles = [engine.submit(p, max_tokens=mt,
                                     temperature=t, seed=s)
                       for p, mt, t, s in reqs]
            for h in handles:
                out.append(h.result(timeout=600.0))
            drafted = sum(h.spec_drafted for h in handles)
        finally:
            engine.shutdown()
        return out, drafted

    ref, _ = run(DecodeEngine(cfg, params, slots=2,
                              max_seq=128).start())
    tp_spec, drafted = run(DecodeEngine(
        cfg, sparams, slots=2, max_seq=128, mesh=mesh, rules=rules,
        paged=True, spec_k=4, spec_ngram=2).start())
    assert tp_spec == ref
    assert drafted > 0


def test_spec_same_admission_sequence_same_tables_and_tokens():
    """The gang lockstep property survives speculation: drafting and
    acceptance are pure functions of the mirrored admission sequence,
    so two engines fed identical submissions step-for-step allocate
    identical block tables (including verify growth + rejected-suffix
    truncation) and emit identical streams."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    seq = _mixed_specs(cfg, seed=6, n=6)

    def run():
        eng = DecodeEngine(cfg, params, slots=3, max_seq=64,
                           prefill_chunk=8, paged=True, spec_k=4,
                           spec_ngram=2)
        reqs = [eng.submit(p, max_tokens=mt) for p, mt in seq]
        tables = []
        for _ in range(400):
            eng._admit()
            tables.append(eng._table.copy())
            did = eng._prefill_one()
            did = eng._decode_step() or did
            if not did and not eng._waiting:
                break
        return ([r.result(timeout=5.0) for r in reqs],
                sum(r.spec_drafted for r in reqs), tables)

    toks_a, drafted_a, tables_a = run()
    toks_b, drafted_b, tables_b = run()
    assert toks_a == toks_b
    assert drafted_a == drafted_b > 0
    assert len(tables_a) == len(tables_b)
    for ta, tb in zip(tables_a, tables_b):
        np.testing.assert_array_equal(ta, tb)


# ======================================================= draft matcher
def test_spec_ngram_draft_lookup_and_self_match_protection():
    """The incremental index proposes the MOST RECENT earlier
    occurrence's continuation, never matches the lookup pattern
    against itself, and clamps drafts to remaining - 1."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=1, max_seq=64,
                       prefill_chunk=8, spec_k=4, spec_ngram=2)
    slot = eng._slots[0]
    req = eng.submit(list(range(1, 9)), max_tokens=20)
    eng._admit()
    assert slot.request is req
    # Draft state seeds LAZILY on the compute path (first prefill
    # touch), never under the admission lock — an un-seeded slot
    # simply has no draft.
    assert not slot.history and eng._draft(slot) == []
    eng._spec_init(slot, req)
    # History [1..8]: trailing bigram (7, 8) has no earlier occurrence.
    assert eng._draft(slot) == []
    # Feed a repeat of an interior bigram: (3, 4) occurred at s=2, its
    # continuation is [5, 6, 7, 8] — exactly the k=4 draft.
    for tok in (3, 4):
        slot.generated += 1
        eng._spec_track(slot, tok)
    assert eng._draft(slot) == [5, 6, 7, 8]
    # Most recent occurrence wins: append (3, 4) -> 9; the trailing
    # (3, 4) now resolves to the later occurrence, whose continuation
    # starts with 9.
    for tok in (9, 3, 4):
        slot.generated += 1
        eng._spec_track(slot, tok)
    assert eng._draft(slot)[0] == 9
    # remaining - 1 clamp: 13 generated of 20 -> k = min(4, 6).
    assert len(eng._draft(slot)) <= 4
    slot.generated = 19
    assert eng._draft(slot) == []             # one token owed: no draft


def test_spec_auto_disable_below_min_accept():
    """A slot whose drafts keep getting rejected stops drafting once
    >= 16 drafted tokens fall below the acceptance floor — the verify
    window stops widening for traffic that never repeats — and the
    stream stays bit-identical throughout."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    prompt = [5, 6, 7] * 6

    def run(min_accept):
        eng = DecodeEngine(cfg, params, slots=1, max_seq=64,
                           prefill_chunk=8, spec_k=4, spec_ngram=2,
                           spec_min_accept=min_accept)
        req = eng.submit(prompt, max_tokens=40)
        _drive(eng)
        return req.result(timeout=5.0), req.spec_drafted, \
            eng._slots[0]

    # min_accept > 1 is unreachable: drafting must shut off right
    # after the 16-draft grace window instead of running forever.
    toks_off, drafted_off, _ = run(min_accept=1.5)
    toks_on, drafted_on, _ = run(min_accept=0.0)
    assert toks_off == toks_on                # parity is unconditional
    assert drafted_on > drafted_off
    assert drafted_off <= 16 + 4              # grace window + one step


# ============================================== lifecycle + pool refs
def test_spec_cancel_mid_verify_releases_pool_refs():
    """Cancel landing between verify steps of a speculating paged slot
    releases every pool reference: aliased prefix pins drop, grown
    decode blocks free, reservations return — the churn identity
    free + trie == usable holds with zero refs outstanding."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, spec_k=4,
                       spec_ngram=2)
    shared = [5, 6, 7] * 6                    # 18 tokens: 2 full chunks
    first = eng.submit(shared, max_tokens=1)
    _drive(eng)
    assert first.result(timeout=5.0)
    assert eng.prefix_cache.stats()["chunks"] == 2

    req = eng.submit(shared + [9, 9, 9], max_tokens=16)
    eng._admit()
    assert eng._slots[0].held                 # aliased prefix pinned
    # Run prefill + a couple of verify steps so the slot is
    # mid-speculation with grown decode blocks, then cancel.
    for _ in range(6):
        eng._prefill_one()
        eng._decode_step()
    assert req.spec_drafted > 0               # really mid-verify
    req.cancel()
    _drive(eng)
    try:
        req.result(timeout=5.0)
    except EngineError:
        pass                                  # cancelled is clean either way
    pool = eng._pool
    assert all(s.request is None for s in eng._slots)
    assert pool.free_blocks() + len(eng.prefix_cache.nodes()) == \
        pool.usable_blocks
    assert pool._reserved == 0
    assert all(n.refs == 0 for n in eng.prefix_cache.nodes())


def test_spec_churn_500_cycles_accounting_clean():
    """The paged 500-cycle admit/cancel churn holds its accounting
    identity with speculation armed — verify growth, truncation and
    cancel interleave without leaking a block or a reservation."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, spec_k=4,
                       spec_ngram=2)
    rng = random.Random(7)
    for _ in range(500):
        if rng.random() < 0.4:                # draft-friendly mix
            motif = [rng.randint(1, 127)] * 2
            prompt = motif * rng.randint(5, 12)
        else:
            prompt = [rng.randint(1, 127)
                      for _ in range(rng.randint(9, 30))]
        req = eng.submit(prompt, max_tokens=rng.randint(1, 6))
        eng._admit()
        for _ in range(rng.randint(0, 5)):
            did = eng._prefill_one()
            did = eng._decode_step() or did
            if not did:
                break
        req.cancel()
        _drive(eng)
    pool = eng._pool
    assert all(s.request is None for s in eng._slots)
    assert pool.free_blocks() + len(eng.prefix_cache.nodes()) == \
        pool.usable_blocks
    assert pool._reserved == 0
    assert all(n.refs == 0 for n in eng.prefix_cache.nodes())


# ================================================== chaos + supervisor
def test_spec_injected_verify_fault_rides_restart_ladder():
    """An injected ``engine.verify`` fault crashes the compute loop
    like any real verify-step failure; the EngineSupervisor restarts a
    fresh engine and the replacement serves bit-identical tokens."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    prompt = [5, 6, 7] * 6

    def factory():
        return DecodeEngine(cfg, params, slots=1, max_seq=64,
                            prefill_chunk=8, paged=True, spec_k=4,
                            spec_ngram=2)

    sup = decode_engine.EngineSupervisor(
        factory, backoff_base=0.05, poll_interval=0.02).start()
    try:
        with fault_injection.inject("engine.verify", times=1):
            req = sup.submit(prompt, max_tokens=10)
            with pytest.raises(EngineError):
                req.result(timeout=60.0)
        deadline = 30.0
        import time
        t0 = time.monotonic()
        while not sup.healthy():
            assert time.monotonic() - t0 < deadline, \
                "supervisor never restarted the engine"
            time.sleep(0.05)
        assert sup.restarts == 1
        got = sup.submit(prompt, max_tokens=10).result(timeout=60.0)
        ref_eng = DecodeEngine(cfg, params, slots=1, max_seq=64,
                               prefill_chunk=8)
        ref = ref_eng.submit(prompt, max_tokens=10)
        _drive(ref_eng)
        assert got == ref.result(timeout=5.0)
    finally:
        sup.shutdown()


# ============================================ telemetry + env contract
def test_spec_counters_and_metrics_surface():
    """Drafted/accepted counters and the acceptance-rate histogram
    land in the process registry (and therefore the replica /metrics
    -> LB merge)."""
    from skypilot_tpu.observability import metrics as metrics_lib
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    drafted_before = metrics_lib.REGISTRY.counter(
        "stpu_engine_spec_drafted_tokens_total").get()
    eng = DecodeEngine(cfg, params, slots=1, max_seq=64,
                       prefill_chunk=8, spec_k=4,
                       spec_ngram=2).start()
    try:
        eng.submit([5, 6, 7] * 6, max_tokens=10).result(timeout=300.0)
    finally:
        eng.shutdown()
    assert metrics_lib.REGISTRY.counter(
        "stpu_engine_spec_drafted_tokens_total").get() > drafted_before
    text = metrics_lib.render()
    assert "stpu_engine_spec_drafted_tokens_total" in text
    assert "stpu_engine_spec_accepted_tokens_total" in text
    assert "stpu_engine_spec_accept_rate_count" in text


def test_spec_stepstats_and_perf_snapshot_carry_acceptance():
    """Armed stepstats records per-step drafted/accepted counts and
    snapshot() (the replica /perf document, which `stpu perf`
    renders) derives the live acceptance rate from the ring."""
    from skypilot_tpu import cli as cli_mod
    from skypilot_tpu.observability import stepstats
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    was_armed = stepstats.ENABLED
    stepstats.arm(ring=512)
    stepstats.reset()
    try:
        eng = DecodeEngine(cfg, params, slots=1, max_seq=64,
                           prefill_chunk=8, spec_k=4,
                           spec_ngram=2).start()
        try:
            req = eng.submit([5, 6, 7] * 6, max_tokens=10)
            req.result(timeout=300.0)
        finally:
            eng.shutdown()
        assert req.spec_drafted > 0
        recs = stepstats.steps_tail()
        assert sum(r.get("spec_drafted", 0) for r in recs) == \
            req.spec_drafted
        snap = stepstats.snapshot()
        assert snap["spec"]["drafted"] == req.spec_drafted
        assert snap["spec"]["accepted"] == req.spec_accepted
        assert 0.0 <= snap["spec"]["accept_rate"] <= 1.0
        rendered = "\n".join(cli_mod._perf_snapshot_lines(snap))
        assert "accept" in rendered and "drafted" in rendered
    finally:
        stepstats.reset()
        if not was_armed:
            stepstats.disarm()


def test_spec_env_knobs_registered_and_in_handshake_geometry():
    """STPU_SPEC_* are registered (stpu-env stays green), the paged
    default is flipped to 1, and the spec knobs ride the effective
    kv-handshake geometry so a gang member drafting differently fails
    the welcome comparison instead of silently diverging tokens."""
    from skypilot_tpu.utils import env_contract
    assert env_contract.get("STPU_SPEC_K").default == "0"
    assert env_contract.get("STPU_SPEC_NGRAM").default == "3"
    assert env_contract.get("STPU_SPEC_MIN_ACCEPT").default == "0.2"
    assert env_contract.get("STPU_KV_PAGED").default == "1"

    geo = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, prefill_chunk=8, paged=True, spec_k=4,
        spec_ngram=2, spec_min_accept=0.25)
    assert geo["spec_k"] == 4 and geo["spec_ngram"] == 2
    assert geo["spec_min_accept"] == 0.25
    other = decode_engine.resolve_kv_geometry(
        slots=2, max_seq=64, prefill_chunk=8, paged=True, spec_k=0)
    assert other != geo                       # mismatch is fatal at join

    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True, spec_k=4,
                       spec_ngram=2, spec_min_accept=0.25)
    assert eng.kv_config() == geo             # single derivation


def test_serve_llm_default_is_paged_with_spec_selectable():
    """The serving default is the paged pool (STPU_KV_PAGED flipped to
    1); spec stays opt-in, and a spec-armed replica serves the same
    tokens over HTTP as the models' fixed path."""
    import json
    import urllib.request
    from skypilot_tpu.recipes import serve_llm
    assert serve_llm.ENGINE_KV_PAGED is True
    assert serve_llm.ENGINE_SPEC_K == 0

    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=2, spec_k=3)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=300)
        assert httpd.engine.engine._paged    # serving default
        assert httpd.engine.engine._spec_k == 3
        port = httpd.server_address[1]
        prompt = [5, 6, 7] * 6
        body = json.dumps({"prompt": prompt,
                           "max_tokens": 8}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            toks = json.loads(resp.read())["tokens"]
        ref_eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                               prefill_chunk=8)
        ref = ref_eng.submit(prompt, max_tokens=8)
        _drive(ref_eng)
        assert toks == ref.result(timeout=5.0)
    finally:
        httpd.shutdown()
