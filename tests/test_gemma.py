"""Gemma family: architecture deltas, training, decode consistency.

The family exists to exercise the shared llama kernel stack's
generality (RMSNorm (1+w) offset, GeGLU, MQA, head_dim decoupled from
dim/n_heads) — so these tests pin exactly those deltas, then run the
same train/decode contracts the other families have.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import gemma, llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer


def test_architecture_deltas_active():
    """The three gemma knobs actually change the computation (a silent
    fall-through to llama semantics would pass every other test)."""
    cfg = gemma.GemmaConfig.tiny(vocab_size=64)
    params = gemma.init(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, 64)

    # Norm weights init to ZEROS; with offset 1 the scale is identity,
    # so the forward must produce finite, non-degenerate logits.
    assert float(jnp.abs(params["final_norm"]).max()) == 0.0
    logits = gemma.forward(cfg, params, tokens)
    assert bool(jnp.isfinite(logits).all())
    assert float(jnp.std(logits)) > 0.01

    # Tied head: no lm_head leaf; head_weights is embed^T.
    assert "lm_head" not in params
    np.testing.assert_array_equal(
        np.asarray(gemma.head_weights(params)),
        np.asarray(params["embed"].T))

    # MQA + decoupled head_dim in the actual weight shapes.
    assert cfg.n_kv_heads == 1
    assert cfg.head_dim != cfg.dim // cfg.n_heads
    assert params["layers"]["wk"].shape == (
        cfg.n_layers, cfg.dim, cfg.head_dim)

    # Each knob changes the logits when disabled -> they are all live.
    for override in ({"norm_offset": 0.0},
                     {"mlp_activation": "silu"}):
        other = dataclasses.replace(cfg, **override)
        changed = gemma.forward(other, params, tokens)
        assert not np.allclose(np.asarray(changed), np.asarray(logits)), \
            f"{override} had no effect"
    # embed_multiplier is a property (sqrt(dim)); check it is applied by
    # comparing against the shared trunk with a scale-1 lookalike.
    class _NoScale(gemma.GemmaConfig):
        embed_multiplier = 1.0
    noscale = _NoScale(**dataclasses.asdict(cfg))
    changed = llama.forward(noscale, params, tokens)
    assert not np.allclose(np.asarray(changed), np.asarray(logits))


def test_untied_head_honored():
    """tie_embeddings=False is a real knob, not a dead config field:
    init creates an lm_head, param_specs names it, num_params counts
    it, flops_per_token doubles the vocab-projection term, and the
    forward actually USES the untied weights."""
    cfg = gemma.GemmaConfig.tiny(vocab_size=64)
    untied_cfg = dataclasses.replace(cfg, tie_embeddings=False)
    tied = gemma.init(cfg, jax.random.key(0))
    untied = gemma.init(untied_cfg, jax.random.key(0))

    assert "lm_head" in untied
    assert untied["lm_head"].shape == (cfg.dim, cfg.vocab_size)
    assert "lm_head" in gemma.param_specs(untied_cfg)
    assert "lm_head" not in gemma.param_specs(cfg)

    # Config accounting and the real tree agree, for BOTH settings —
    # the drift this knob used to hide.
    for c, p in ((cfg, tied), (untied_cfg, untied)):
        actual = sum(int(x.size) for x in jax.tree.leaves(p))
        assert c.num_params() == actual, (c.tie_embeddings, actual)
    extra = cfg.vocab_size * cfg.dim
    assert untied_cfg.num_params() - cfg.num_params() == extra
    assert untied_cfg.flops_per_token() - cfg.flops_per_token() == \
        6.0 * extra

    # The untied head is live in the forward: swapping it changes
    # logits; head_weights returns it (not embed^T).
    np.testing.assert_array_equal(
        np.asarray(gemma.head_weights(untied)),
        np.asarray(untied["lm_head"]))
    tokens = jax.random.randint(jax.random.key(1), (1, 6), 0, 64)
    base = gemma.forward(untied_cfg, untied, tokens)
    swapped = dict(untied, lm_head=untied["lm_head"] * 2.0)
    changed = gemma.forward(untied_cfg, swapped, tokens)
    assert not np.allclose(np.asarray(base), np.asarray(changed))


def test_gemma_train_loss_decreases():
    cfg = gemma.GemmaConfig.tiny(vocab_size=128)
    mesh = mesh_lib.make_mesh({"dp": 1}, devices=[jax.devices()[0]])
    params = gemma.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(
        warmup_steps=1, total_steps=100, learning_rate=1e-2))
    state = trainer.init_train_state(params, tx)
    step = trainer.make_train_step(
        lambda p, t, constrain: gemma.forward(cfg, p, t,
                                              constrain=constrain),
        tx, mesh, mesh_lib.DEFAULT_RULES)
    batch = {"tokens": jax.random.randint(jax.random.key(1), (4, 64),
                                          0, 128)}
    state, m0 = step(state, batch)
    for _ in range(15):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"]) * 0.7, \
        (float(m0["loss"]), float(m["loss"]))


def test_gemma_fsdp_sharded_train_step():
    """The spec tree drives a multi-device fsdp layout exactly like
    llama's (the point of sharing the spec vocabulary)."""
    cfg = gemma.GemmaConfig.tiny(vocab_size=128)
    mesh = mesh_lib.make_mesh({"fsdp": -1})  # all 8 virtual devices
    params = gemma.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(
        warmup_steps=1, total_steps=100))
    state = trainer.init_train_state(params, tx)
    state = jax.device_put(
        state, trainer.state_shardings(mesh, mesh_lib.DEFAULT_RULES,
                                       gemma.param_specs(cfg), state))
    step = trainer.make_train_step(
        lambda p, t, constrain: gemma.forward(cfg, p, t,
                                              constrain=constrain),
        tx, mesh, mesh_lib.DEFAULT_RULES)
    batch = {"tokens": jax.random.randint(jax.random.key(1), (8, 64),
                                          0, 128)}
    state, metrics = step(state, batch)
    assert bool(jnp.isfinite(metrics["loss"]))


def test_gemma_cached_decode_matches_forward():
    """Prefill + cached steps == re-running the full forward each step —
    the serving contract, through the SHARED decode loop."""
    cfg = gemma.GemmaConfig.tiny(vocab_size=128)
    params = gemma.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, 128)
    toks = gemma.decode(cfg, params, prompt, jnp.int32(8),
                        max_tokens=4, max_seq=16)
    assert toks.shape == (2, 4)

    seq = prompt
    expected = []
    for _ in range(4):
        logits = gemma.forward(cfg, params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        expected.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    expected = jnp.stack(expected, axis=1)
    assert (toks == expected).all(), (toks, expected)


def test_gemma_lora_recipe_runs(tmp_path):
    from skypilot_tpu.recipes import gemma_lora
    m = gemma_lora.main(["--model", "tiny", "--steps", "8",
                         "--batch-size", "2", "--seq-len", "64",
                         "--checkpoint-dir", str(tmp_path / "ck")])
    assert m["recipe"] == "gemma_lora"
    assert m["final_loss"] < m["first_loss"]
    # Adapters are the only trainables and they are small.
    assert m["lora_params"] < m["base_params"] * 0.2


def test_serve_llm_gemma_endpoint():
    """The serving recipe's dispatch covers gemma end-to-end (same
    contract as the mixtral endpoint test)."""
    import json
    import threading
    import urllib.request

    from skypilot_tpu.recipes import serve_llm
    cfg = gemma.GemmaConfig.tiny(vocab_size=128)
    params = gemma.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=180)
        req = urllib.request.Request(
            f"http://127.0.0.1:{httpd.server_address[1]}/generate",
            data=json.dumps({"prompt": [1, 2, 3],
                             "max_tokens": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert len(out["tokens"]) == 4
        assert all(0 <= t < 128 for t in out["tokens"])
    finally:
        httpd.shutdown()


def test_gemma_tp_sharded_train_step():
    """dp×tp mesh: MQA is the tp edge case — ONE kv head means the kv
    projection shards over the flattened (kv_heads × head_dim) columns,
    not over heads; the shared spec vocabulary must still produce a
    runnable layout."""
    cfg = gemma.GemmaConfig.tiny(vocab_size=128)
    mesh = mesh_lib.make_mesh({"dp": 2, "tp": 4})
    params = gemma.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(
        warmup_steps=1, total_steps=100))
    state = trainer.init_train_state(params, tx)
    state = jax.device_put(
        state, trainer.state_shardings(mesh, mesh_lib.DEFAULT_RULES,
                                       gemma.param_specs(cfg), state))
    step = trainer.make_train_step(
        lambda p, t, constrain: gemma.forward(cfg, p, t,
                                              constrain=constrain),
        tx, mesh, mesh_lib.DEFAULT_RULES)
    batch = {"tokens": jax.random.randint(jax.random.key(1), (8, 64),
                                          0, 128)}
    state, metrics = step(state, batch)
    assert bool(jnp.isfinite(metrics["loss"]))
