"""Native host-agent core: barrier, heartbeat failure detection, clean
departure — for BOTH the C++ library (built with g++ on first use) and the
pure-Python protocol twin, which must interoperate.

Reference analog: the coordination behaviors the reference gets from Ray
placement groups + node liveness (cloud_vm_ray_backend.py:296-505); TSAN
note in SURVEY §5 — the C++ core is also exercised here under load.
"""
import threading
import time

import pytest

from skypilot_tpu.agent import native
from skypilot_tpu.agent.native import _PyClient, _PyCoordinator


def _native_pair():
    if not native.native_available():
        pytest.skip("no g++ toolchain for the native agent")
    return native._NativeCoordinator, native._NativeClient


IMPLS = [
    pytest.param("native", id="native"),
    pytest.param("python", id="python"),
]


def _impl(kind):
    if kind == "native":
        return _native_pair()
    return _PyCoordinator, _PyClient


@pytest.mark.parametrize("kind", IMPLS)
@pytest.mark.usefixtures("tmp_state_dir")
def test_barrier_all_ranks(kind):
    Coordinator, Client = _impl(kind)
    coord = Coordinator(4, heartbeat_timeout_ms=5000)
    results = {}

    def worker(rank):
        c = Client("127.0.0.1", coord.port, rank, timeout_ms=5000)
        results[rank] = c.barrier(0, timeout_ms=5000)
        c.close()

    ts = [threading.Thread(target=worker, args=(r,)) for r in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    coord.close()
    assert results == {0: 0, 1: 0, 2: 0, 3: 0}


@pytest.mark.parametrize("kind", IMPLS)
@pytest.mark.usefixtures("tmp_state_dir")
def test_barrier_blocks_until_all_arrive(kind):
    """A host must not pass the barrier before the slowest host is up."""
    Coordinator, Client = _impl(kind)
    coord = Coordinator(2, heartbeat_timeout_ms=5000)
    t_done = {}

    def fast():
        c = Client("127.0.0.1", coord.port, 0, timeout_ms=5000)
        assert c.barrier(0, timeout_ms=5000) == 0
        t_done[0] = time.time()
        c.close()

    th = threading.Thread(target=fast)
    th.start()
    time.sleep(0.6)  # slow host arrives late
    c1 = Client("127.0.0.1", coord.port, 1, timeout_ms=5000)
    t1_start = time.time()
    assert c1.barrier(0, timeout_ms=5000) == 0
    th.join()
    c1.close()
    coord.close()
    assert t_done[0] >= t1_start - 0.05  # rank 0 released only after 1


@pytest.mark.parametrize("kind", IMPLS)
@pytest.mark.usefixtures("tmp_state_dir")
def test_dead_rank_fails_barrier_and_gang(kind):
    Coordinator, Client = _impl(kind)
    coord = Coordinator(3, heartbeat_timeout_ms=3000)
    clients = [Client("127.0.0.1", coord.port, r, timeout_ms=5000)
               for r in range(3)]
    assert coord.wait_ready(5000) == 0
    clients[1].abort()  # dirty death, no goodbye
    assert clients[0].barrier(1, timeout_ms=5000) == -3  # -2 - rank1
    assert coord.failed_rank == 1
    # The FAIL broadcast reaches client 2's reader asynchronously.
    deadline = time.time() + 5
    while clients[2].failed_rank < 0 and time.time() < deadline:
        time.sleep(0.05)
    assert clients[2].failed_rank == 1
    for c in clients:
        c.close()
    coord.close()


@pytest.mark.parametrize("kind", IMPLS)
@pytest.mark.usefixtures("tmp_state_dir")
def test_clean_goodbye_is_not_failure(kind):
    Coordinator, Client = _impl(kind)
    coord = Coordinator(2, heartbeat_timeout_ms=2000)
    c0 = Client("127.0.0.1", coord.port, 0, timeout_ms=5000)
    c1 = Client("127.0.0.1", coord.port, 1, timeout_ms=5000)
    assert coord.wait_ready(5000) == 0
    c0.close()  # clean departure
    time.sleep(1.0)
    assert coord.failed_rank == -1
    assert c1.failed_rank == -1
    c1.close()
    coord.close()


@pytest.mark.parametrize("kind", IMPLS)
@pytest.mark.usefixtures("tmp_state_dir")
def test_wait_ready_times_out_without_all_hosts(kind):
    Coordinator, Client = _impl(kind)
    coord = Coordinator(2, heartbeat_timeout_ms=5000)
    c0 = Client("127.0.0.1", coord.port, 0, timeout_ms=5000)
    assert coord.wait_ready(300) == -1
    assert coord.registered_count == 1
    c0.close()
    coord.close()


@pytest.mark.parametrize("kind", IMPLS)
@pytest.mark.usefixtures("tmp_state_dir")
def test_stray_connection_does_not_hang_close(kind):
    """A peer that connects but never registers (port scanner, health
    check) must not leave a reader blocked forever: close() returns
    promptly and registered hosts still work."""
    import socket as socket_mod

    Coordinator, Client = _impl(kind)
    coord = Coordinator(2, heartbeat_timeout_ms=5000)
    stray = socket_mod.create_connection(("127.0.0.1", coord.port))
    c0 = Client("127.0.0.1", coord.port, 0, timeout_ms=5000)
    c1 = Client("127.0.0.1", coord.port, 1, timeout_ms=5000)
    assert coord.wait_ready(5000) == 0
    c0.close()
    c1.close()
    t0 = time.time()
    coord.close()  # must not join a reader stuck on the stray fd
    assert time.time() - t0 < 5.0
    stray.close()


@pytest.mark.usefixtures("tmp_state_dir")
def test_coordinator_binds_loopback_only():
    """The unauthenticated protocol must not be reachable from the
    network: both implementations bind 127.0.0.1."""
    import socket as socket_mod

    for Coordinator, _ in (_impl("python"),) + (
            (_impl("native"),) if native.native_available() else ()):
        coord = Coordinator(1, heartbeat_timeout_ms=5000)
        hostname_ip = socket_mod.gethostbyname(socket_mod.gethostname())
        if hostname_ip != "127.0.0.1":
            with pytest.raises(OSError):
                socket_mod.create_connection((hostname_ip, coord.port),
                                             timeout=1).close()
        coord.close()


@pytest.mark.usefixtures("tmp_state_dir")
def test_native_and_python_interoperate():
    """Mixed gang: native coordinator, python client (and vice versa) —
    same wire protocol."""
    if not native.native_available():
        pytest.skip("no g++ toolchain")
    coord = native._NativeCoordinator(2, heartbeat_timeout_ms=5000)
    results = {}

    def py_worker():
        c = _PyClient("127.0.0.1", coord.port, 0, timeout_ms=5000)
        results["py"] = c.barrier(0, timeout_ms=5000)
        c.close()

    def native_worker():
        c = native._NativeClient("127.0.0.1", coord.port, 1,
                                 timeout_ms=5000)
        results["native"] = c.barrier(0, timeout_ms=5000)
        c.close()

    ts = [threading.Thread(target=py_worker),
          threading.Thread(target=native_worker)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    coord.close()
    assert results == {"py": 0, "native": 0}

    coord = _PyCoordinator(1, heartbeat_timeout_ms=5000)
    c = native._NativeClient("127.0.0.1", coord.port, 0, timeout_ms=5000)
    assert c.barrier(0, timeout_ms=5000) == 0
    c.close()
    coord.close()


@pytest.mark.usefixtures("tmp_state_dir")
def test_heartbeat_timeout_detects_hang():
    """A rank that stops heartbeating (hung host) is declared failed even
    though its connection stays open."""
    coord = _PyCoordinator(2, heartbeat_timeout_ms=800)
    c0 = _PyClient("127.0.0.1", coord.port, 0, timeout_ms=5000,
                   heartbeat_interval_ms=200)
    c1 = _PyClient("127.0.0.1", coord.port, 1, timeout_ms=5000,
                   heartbeat_interval_ms=200)
    assert coord.wait_ready(5000) == 0
    c1._stop = True  # freeze rank 1's heartbeat thread, socket stays open
    deadline = time.time() + 5
    while coord.failed_rank < 0 and time.time() < deadline:
        time.sleep(0.1)
    assert coord.failed_rank == 1
    assert c0.failed_rank == 1
    c0.close()
    c1.close()
    coord.close()


@pytest.mark.usefixtures("tmp_state_dir")
def test_gang_exec_uses_barrier_for_synchronized_start(tmp_path):
    """End-to-end: a 3-host local gang starts all hosts within a tight
    window even when the driver staggers process creation."""
    import time as time_mod

    from skypilot_tpu import execution
    from skypilot_tpu.agent import job_lib
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    task = Task("barriercheck",
                run="date +%s.%N > ~/start_ts; sleep 0.2", num_nodes=3)
    task.set_resources(Resources(cloud="local"))
    job_id, handle = execution.launch(task, cluster_name="t-barrier",
                                      detach_run=True, stream_logs=False)
    deadline = time_mod.time() + 60
    while time_mod.time() < deadline:
        job = job_lib.get_job(job_id, home=handle.head_home)
        if job and job_lib.JobStatus(job["status"]).is_terminal():
            break
        time_mod.sleep(0.2)
    assert job["status"] == "SUCCEEDED"
    stamps = []
    for inst in handle.cluster_info.ordered_instances():
        stamps.append(float(
            open(inst.tags["host_dir"] + "/start_ts").read().strip()))
    spread = max(stamps) - min(stamps)
    assert spread < 2.0, f"start spread too wide: {stamps}"
