"""Ring attention vs single-device reference on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.parallel import mesh as mesh_lib, ring_attention


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(causal):
    mesh = mesh_lib.make_mesh({"dp": 2, "sp": 4})
    b, s, h, kvh, d = 2, 128, 4, 2, 32
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, s, h, d))
    k = jax.random.normal(kk, (b, s, kvh, d))
    v = jax.random.normal(kv, (b, s, kvh, d))
    out = jax.jit(lambda q, k, v: ring_attention.ring_attention(
        q, k, v, mesh=mesh, causal=causal))(q, k, v)
    ref = attention_ops._reference_attention(q, k, v, causal=causal,
                                             scale=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_gradients_match_reference():
    mesh = mesh_lib.make_mesh({"sp": 8})
    b, s, h, kvh, d = 1, 64, 2, 2, 16
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, (b, s, h, d))
    k = jax.random.normal(kk, (b, s, kvh, d))
    v = jax.random.normal(kv, (b, s, kvh, d))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention.ring_attention(
            q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_ops._reference_attention(
            q, k, v, causal=True, scale=None) ** 2)

    gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-3, atol=1e-3)


def test_ring_no_sp_axis_falls_back():
    mesh = mesh_lib.make_mesh({"dp": 8})
    b, s, h, d = 1, 32, 2, 16
    q = jax.random.normal(jax.random.key(0), (b, s, h, d))
    out = ring_attention.ring_attention(q, q, q, mesh=mesh)
    ref = attention_ops._reference_attention(q, q, q, causal=True,
                                             scale=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_llama_with_ring_attention_end_to_end():
    """attention_impl='ring' through the trainer context."""
    import dataclasses
    from skypilot_tpu.models import llama
    from skypilot_tpu.train import trainer

    mesh = mesh_lib.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=64),
                              attention_impl="ring")
    params = llama.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(warmup_steps=1,
                                                    total_steps=20))
    state = trainer.init_train_state(params, tx)
    step = trainer.make_train_step(
        lambda p, t, constrain: llama.forward(cfg, p, t,
                                              constrain=constrain),
        tx, mesh, mesh_lib.DEFAULT_RULES)
    tokens = jax.random.randint(jax.random.key(1), (4, 64), 0, 64)
    state, m0 = step(state, {"tokens": tokens})
    for _ in range(5):
        state, m = step(state, {"tokens": tokens})
    assert float(m["loss"]) < float(m0["loss"])


def test_ring_multiblock_chunk_path(monkeypatch):
    """Force n_blocks > 1 inside each ring chunk (the long-context
    regime: _KV_BLOCK sub-blocking + kpos offsets + divisor fallback),
    which default test shapes never reach."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.ops import attention as attention_ops
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import ring_attention

    monkeypatch.setattr(ring_attention, "_KV_BLOCK", 8)
    mesh = mesh_lib.make_mesh({"sp": 4, "tp": 2})
    b, s, h, kvh, d = 1, 128, 4, 2, 16   # per-shard 32 -> 4 sub-blocks
    q = jax.random.normal(jax.random.key(0), (b, s, h, d), jnp.float32)
    k = jax.random.normal(jax.random.key(1), (b, s, kvh, d), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, s, kvh, d), jnp.float32)
    out = jax.jit(lambda q, k, v: ring_attention.ring_attention(
        q, k, v, mesh=mesh))(q, k, v)
    ref = attention_ops._reference_attention(q, k, v, causal=True,
                                             scale=d ** -0.5)
    assert float(jnp.max(jnp.abs(out - ref))) < 2e-5
    # Odd per-shard length exercises the block //= 2 divisor fallback.
    s2 = 120   # per-shard 30 -> block halves to 2? (30 % 8 != 0)
    q2 = jax.random.normal(jax.random.key(3), (b, s2, h, d), jnp.float32)
    k2 = jax.random.normal(jax.random.key(4), (b, s2, kvh, d), jnp.float32)
    v2 = jax.random.normal(jax.random.key(5), (b, s2, kvh, d), jnp.float32)
    out2 = jax.jit(lambda q, k, v: ring_attention.ring_attention(
        q, k, v, mesh=mesh))(q2, k2, v2)
    ref2 = attention_ops._reference_attention(q2, k2, v2, causal=True,
                                              scale=d ** -0.5)
    assert float(jnp.max(jnp.abs(out2 - ref2))) < 2e-5


@pytest.mark.parametrize("causal", [True, False])
def test_ring_prime_chunk_length_pads(causal, monkeypatch):
    """Per-shard chunk lengths with no decent divisor (ADVICE r3 #2):
    the pad-and-mask path must stay exact — a degenerate width-1 block
    scan was correct but pathological, and a WRONG pad mask would leak
    zero-key weight into the softmax."""
    # Floor above the largest divisor of 61 (prime) forces padding.
    monkeypatch.setattr(ring_attention, "_KV_BLOCK", 16)
    monkeypatch.setattr(ring_attention, "_KV_BLOCK_FLOOR", 8)
    mesh = mesh_lib.make_mesh({"dp": 4, "sp": 2})
    b, s, h, kvh, d = 4, 122, 2, 1, 16   # chunk length 61, prime
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(kq, (b, s, h, d))
    k = jax.random.normal(kk, (b, s, kvh, d))
    v = jax.random.normal(kv, (b, s, kvh, d))
    out = jax.jit(lambda q, k, v: ring_attention.ring_attention(
        q, k, v, mesh=mesh, causal=causal))(q, k, v)
    ref = attention_ops._reference_attention(q, k, v, causal=causal,
                                             scale=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
