"""Self-hosted control plane, end-to-end on the local provider.

The architecture under test (reference: sky/jobs/core.py:30 +
templates/jobs-controller.yaml.j2): managed-job and serve controllers run
on launched controller *clusters*, not on the client. The defining
property — verified here — is that the client process can exit after
submission and spot-preemption recovery still happens, driven entirely by
the controller cluster.
"""
import json
import os
import pathlib
import subprocess
import sys
import time
import urllib.request

import pytest

from skypilot_tpu import global_user_state
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.resources import Resources
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import serve_state
from skypilot_tpu.task import Task
from skypilot_tpu.utils import controller_utils

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fast_ticks(monkeypatch):
    monkeypatch.setenv("STPU_JOBS_POLL_SECONDS", "0.2")
    monkeypatch.setenv("STPU_SERVE_TICK_SECONDS", "0.3")


def _controller_host_home(kind: controller_utils.Controllers
                          ) -> pathlib.Path:
    record = global_user_state.get_cluster_from_name(kind.cluster_name)
    assert record is not None and record["handle"] is not None
    head = record["handle"].cluster_info.get_head_instance()
    return pathlib.Path(head.tags["host_dir"])


def _wait_status(job_id, statuses, timeout=120):
    deadline = time.time() + timeout
    st = None
    while time.time() < deadline:
        st = jobs_core.get_status(job_id)
        if st in statuses:
            return st
        time.sleep(0.3)
    raise TimeoutError(f"managed job {job_id} stuck at {st}")


@pytest.mark.usefixtures("tmp_state_dir")
def test_jobs_survive_client_exit_and_recover(tmp_path):
    """Submit from a client process that then EXITS; preempt the task
    cluster (provider-truth flip on the controller host); recovery must
    complete with no client involvement."""
    marker = tmp_path / "attempts"
    run_cmd = (f'n=$(cat {marker} 2>/dev/null || echo 0); '
               f'echo $((n+1)) > {marker}; '
               f'if [ "$n" -ge 1 ]; then echo recovered-ok; '
               f'else sleep 120; fi')
    client_code = f"""
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
task = Task("sh-rec", run={run_cmd!r})
task.set_resources(Resources(cloud="local", use_spot=True))
print(jobs_core.launch(task, name="sh-rec"))
"""
    env = {**os.environ, "PYTHONPATH": REPO_ROOT}
    proc = subprocess.run([sys.executable, "-c", client_code],
                          capture_output=True, text=True, env=env,
                          timeout=180)
    assert proc.returncode == 0, proc.stderr
    job_id = int(proc.stdout.strip().splitlines()[-1])
    # The submitting client is gone. Managed-job state lives on the
    # controller cluster, NOT in the client DB:
    assert jobs_state.queue() == []
    assert jobs_core.get_status(job_id) is not None  # via controller RPC

    _wait_status(job_id, {ManagedJobStatus.RUNNING})
    deadline = time.time() + 60
    while not marker.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert marker.exists()

    # Preemption: flip provider truth for the task cluster, which the
    # controller provisioned under ITS OWN state dir on the controller
    # host (the nested-recursive structure of the reference).
    job = jobs_core.get_job(job_id)
    ctrl_home = _controller_host_home(controller_utils.Controllers.JOBS)
    meta_path = (ctrl_home / ".stpu" / "local_clusters" /
                 job["cluster_name"] / "metadata.json")
    assert meta_path.exists(), f"task cluster not under controller home"
    meta = json.loads(meta_path.read_text())
    for info in meta["instances"].values():
        info["status"] = "preempted"
    meta_path.write_text(json.dumps(meta))

    status = _wait_status(
        job_id, {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                 ManagedJobStatus.FAILED_CONTROLLER}, timeout=120)
    assert status == ManagedJobStatus.SUCCEEDED
    assert jobs_core.get_job(job_id)["recovery_count"] >= 1
    assert marker.read_text().strip() == "2"


@pytest.mark.usefixtures("tmp_state_dir")
def test_jobs_self_hosted_cancel_and_queue():
    task = Task("sh-cancel", run="sleep 120")
    task.set_resources(Resources(cloud="local"))
    job_id = jobs_core.launch(task)  # default mode: cluster
    _wait_status(job_id, {ManagedJobStatus.RUNNING})

    q = jobs_core.queue()  # proxied to the controller
    assert [j["job_id"] for j in q] == [job_id]

    cancelled = jobs_core.cancel([job_id])
    assert cancelled == [job_id]
    status = _wait_status(job_id, {ManagedJobStatus.CANCELLED})
    assert status == ManagedJobStatus.CANCELLED
    # Task cluster torn down on the controller host.
    job = jobs_core.get_job(job_id)
    ctrl_home = _controller_host_home(controller_utils.Controllers.JOBS)
    assert not (ctrl_home / ".stpu" / "local_clusters" /
                job["cluster_name"]).exists()


@pytest.mark.usefixtures("tmp_state_dir")
def test_serve_self_hosted_up_status_down():
    task = Task("sh-svc", run=(
        'cd $(mktemp -d) && echo "hello-from-replica" > index.html && '
        'exec python3 -m http.server $SKYPILOT_SERVE_REPLICA_PORT'))
    task.set_resources(Resources(cloud="local"))
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    task.service = SkyServiceSpec(readiness_path="/",
                                  initial_delay_seconds=60,
                                  min_replicas=1)

    name, endpoint = serve_core.up(task, "svc-sh")  # default: cluster
    try:
        got = serve_core.wait_ready(name, timeout=120)
        assert got == endpoint
        with urllib.request.urlopen(endpoint + "/", timeout=5) as resp:
            assert resp.status == 200
            assert "hello-from-replica" in resp.read().decode()
        # Service state lives on the controller cluster, not the client.
        assert serve_state.get_services() == []
        svcs = serve_core.status([name])  # proxied dump
        assert svcs and svcs[0]["service_name"] == name
        assert svcs[0]["replicas"]
    finally:
        assert serve_core.down([name], timeout=90) == [name]
    assert serve_core.status([name]) == []
