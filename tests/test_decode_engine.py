"""Continuous-batching decode engine + ragged KV-cache decode.

The contract under test, from strongest to weakest layer:

  * split-KV (flash-decode-style) attention == dense masked softmax;
  * batched decode with PER-EXAMPLE prompt lengths matches per-request
    sequential decode token-for-token (greedy) — batch composition
    must never change any row's tokens;
  * the engine (slot scheduling, chunked prefill interleaved with
    decode, slot reuse) reproduces the same tokens — including that
    stale K/V left in a reused slot is never attendable;
  * engine counters land in the observability registry and the replica
    /metrics surface.
"""
import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import gemma, llama, mixtral
from skypilot_tpu.observability import metrics
from skypilot_tpu.serve.decode_engine import DecodeEngine, EngineError


def _ragged_prompts(key, lens, vocab):
    return [jax.random.randint(jax.random.key(key + i), (l,), 1, vocab)
            for i, l in enumerate(lens)]


def _pad(prompts, s_pad):
    b = len(prompts)
    out = jnp.zeros((b, s_pad), jnp.int32)
    for i, p in enumerate(prompts):
        out = out.at[i, :p.shape[0]].set(p)
    return out


@pytest.mark.parametrize("seq_len,block", [(32, 4), (30, 8)])
def test_split_kv_matches_dense_reference(seq_len, block):
    """Blocked online-softmax over the ragged cache == one dense
    masked softmax, across block boundaries — including a cache length
    the block does NOT divide (the clamped-overlap tail window)."""
    B, T, KVH, G, D = 2, 3, 2, 2, 8
    q = jax.random.normal(jax.random.key(0), (B, T, KVH, G, D))
    ck = jax.random.normal(jax.random.key(1), (B, seq_len, KVH, D))
    cv = jax.random.normal(jax.random.key(2), (B, seq_len, KVH, D))
    positions = jnp.array([[18, 19, 20], [7, 8, 9]])
    valid = jnp.array([21, 10])

    out = llama._split_kv_attention(q, ck, cv, positions, valid,
                                    block=block)

    kpos = jnp.arange(seq_len)
    mask = ((kpos[None, None, :] <= positions[..., None]) &
            (kpos[None, None, :] < valid[:, None, None]))
    scores = jnp.einsum("btkgd,bskd->bkgts", q, ck) * (D ** -0.5)
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    dense = jnp.einsum("bkgts,bskd->btkgd",
                       jax.nn.softmax(scores, axis=-1), cv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)


def test_ragged_batched_decode_matches_sequential():
    """One batched decode over heterogeneous prompt lengths must equal
    per-request decode token-for-token — the property the fixed-batch
    path enforced by REJECTING (B,) lengths."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    lens, mt, s_pad = [3, 7, 5], 6, 8
    prompts = _ragged_prompts(1, lens, 128)

    got = llama.decode(cfg, params, _pad(prompts, s_pad),
                       jnp.asarray(lens), mt, s_pad + mt)
    for i, p in enumerate(prompts):
        ref = llama.decode(cfg, params, p[None, :], jnp.int32(lens[i]),
                           mt, lens[i] + mt)
        np.testing.assert_array_equal(np.asarray(got[i]),
                                      np.asarray(ref[0]))


@pytest.mark.parametrize("family", ["mixtral", "gemma"])
def test_ragged_decode_other_families(family):
    """The (B,) length contract holds through the shared loop for the
    MoE (dense-routed) and MQA/tied-head families too."""
    mdl = {"mixtral": mixtral, "gemma": gemma}[family]
    cfg = mdl.MixtralConfig.tiny() if family == "mixtral" \
        else mdl.GemmaConfig.tiny(vocab_size=128)
    vocab = cfg.vocab_size
    params = mdl.init(cfg, jax.random.key(0))
    lens, mt, s_pad = [2, 5], 4, 6
    prompts = _ragged_prompts(3, lens, vocab)

    got = mdl.decode(cfg, params, _pad(prompts, s_pad),
                     jnp.asarray(lens), mt, s_pad + mt)
    for i, p in enumerate(prompts):
        ref = mdl.decode(cfg, params, p[None, :], jnp.int32(lens[i]),
                         mt, lens[i] + mt)
        np.testing.assert_array_equal(np.asarray(got[i]),
                                      np.asarray(ref[0]))


def test_decode_rejects_mismatched_length_vector():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init(cfg, jax.random.key(0))
    prompt = jnp.ones((2, 4), jnp.int32)
    with pytest.raises(ValueError, match="scalar or a"):
        llama.decode(cfg, params, prompt, jnp.asarray([1, 2, 3]), 2, 16)


def test_decode_with_donated_preallocated_cache():
    """The caller-allocated-and-donated cache path (bench + serving)
    produces the same tokens as the internal-allocation path, and the
    donation is actually USABLE (return_cache=True puts the cache in
    the jit output, so XLA can alias the donated input to it)."""
    import warnings
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 1, 64)
    ref = llama.decode(cfg, params, prompt, jnp.int32(5), 4, 16)

    decode_jit = jax.jit(
        lambda p, pr, cache: llama.decode(cfg, p, pr, jnp.int32(5), 4,
                                          16, cache=cache,
                                          return_cache=True),
        donate_argnums=(2,))
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "error", message=".*donated buffers were not usable.*")
        got, _ = decode_jit(params, prompt, llama.init_cache(cfg, 2, 16))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_engine_matches_decode_across_slot_reuse():
    """5 ragged greedy requests through 2 slots: every request's
    stream must equal its own fixed-path decode — requests 3..5 reuse
    slots whose rows still hold the previous request's K/V, so any
    leak of stale (masked) cache into attention breaks this."""
    import random
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8).start()
    try:
        rng = random.Random(0)
        specs = [([rng.randint(1, 127)
                   for _ in range(rng.randint(1, 19))],
                  rng.randint(1, 8)) for _ in range(5)]
        reqs = [engine.submit(p, max_tokens=mt) for p, mt in specs]
        for (p, mt), req in zip(specs, reqs):
            got = req.result(timeout=300.0)
            ref = llama.decode(cfg, params,
                               jnp.asarray([p], jnp.int32),
                               jnp.int32(len(p)), mt, len(p) + mt)
            assert got == [int(t) for t in ref[0]], (p, mt)
    finally:
        engine.shutdown()


def test_engine_chunked_prefill_long_prompt():
    """A prompt spanning several prefill chunks (chunk 8, prompt 19)
    must decode identically to the single-pass prefill path."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8).start()
    try:
        prompt = [int(t) for t in jax.random.randint(
            jax.random.key(7), (19,), 1, 128)]
        got = engine.submit(prompt, max_tokens=6).result(timeout=300.0)
        ref = llama.decode(cfg, params, jnp.asarray([prompt]),
                           jnp.int32(19), 6, 32)
        assert got == [int(t) for t in ref[0]]
    finally:
        engine.shutdown()


def test_engine_sampling_reproducible_and_limits():
    """Seeded sampling is slot- and batch-composition-independent;
    oversized and empty requests are rejected upfront."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=2, max_seq=32,
                          prefill_chunk=8).start()
    try:
        r1 = engine.submit([5, 6, 7], max_tokens=5, temperature=0.8,
                           seed=42).result(timeout=300.0)
        # Second run shares the batch with another live request — the
        # fold_in(seed, position) keys must not notice.
        other = engine.submit([9, 9, 9, 9], max_tokens=8)
        r2 = engine.submit([5, 6, 7], max_tokens=5, temperature=0.8,
                           seed=42).result(timeout=300.0)
        other.result(timeout=300.0)
        assert r1 == r2
        with pytest.raises(EngineError, match="exceeds"):
            engine.submit(list(range(1, 30)), max_tokens=16)
        with pytest.raises(EngineError, match="empty"):
            engine.submit([], max_tokens=4)
    finally:
        engine.shutdown()


def test_engine_metrics_in_registry_and_replica_endpoint():
    """Slot/queue gauges and token/TTFT series reach the process
    registry, and the replica serves them on GET /metrics."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    tokens_before = metrics.REGISTRY.counter(
        "stpu_engine_decode_tokens_total").get()

    from skypilot_tpu.recipes import serve_llm
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=300)
        port = httpd.server_address[1]
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert len(json.loads(resp.read())["tokens"]) == 4
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        assert "stpu_engine_slots_total 2" in text
        assert "stpu_engine_queue_depth" in text
        assert "stpu_engine_ttft_seconds_count" in text
        assert metrics.REGISTRY.counter(
            "stpu_engine_decode_tokens_total").get() >= tokens_before + 4
    finally:
        httpd.shutdown()


def test_lb_metrics_include_replica_engine_families():
    """The LB /metrics snapshot merges each ready replica's exposition
    (engine slot/queue/token families) into one scrape."""
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import \
        RoundRobinPolicy

    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    lb = None
    try:
        assert ready.wait(timeout=300)
        policy = RoundRobinPolicy()
        policy.set_ready_replicas(
            [f"http://127.0.0.1:{httpd.server_address[1]}"])
        lb = lb_lib.run_load_balancer(0, policy,
                                      lb_lib.RequestRecorder())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{lb.server_address[1]}/metrics",
                timeout=30) as resp:
            text = resp.read().decode()
        assert "stpu_lb_requests_total" in text       # LB's own
        assert "stpu_engine_slots_total" in text      # replica's
    finally:
        if lb is not None:
            lb.shutdown()
        httpd.shutdown()


def test_serve_llm_legacy_path_still_serves():
    """engine_slots=0 keeps the locked fixed-batch path working (the
    comparability baseline), including its donated-cache _decode."""
    from skypilot_tpu.recipes import serve_llm
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=300)
        assert httpd.engine is None
        port = httpd.server_address[1]
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_tokens": 6}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            toks = json.loads(resp.read())["tokens"]
        ref = llama.decode(cfg, params, jnp.asarray([[1, 2, 3]]),
                           jnp.int32(3), 6, 128)
        assert toks == [int(t) for t in ref[0]][:6]
    finally:
        httpd.shutdown()


# ------------------------------------------------- shared-prefix KV cache
def _tiny_cfg(family):
    if family == "mixtral":
        return mixtral, mixtral.MixtralConfig.tiny()
    if family == "gemma":
        return gemma, gemma.GemmaConfig.tiny(vocab_size=128)
    return llama, llama.LlamaConfig.tiny(vocab_size=128)


@pytest.mark.parametrize("family", ["llama", "mixtral", "gemma"])
def test_prefix_hit_token_identical_and_fewer_steps(family):
    """A prefix-cache hit must change ONLY latency: the warm stream is
    token-identical to the fixed-path (cold) decode, prefill tokens
    are actually saved, and steps-to-first-token (chunk prefills, the
    deterministic TTFT) is STRICTLY lower than the cold run's. Prefix
    caching is the paged pool's zero-copy aliasing — the only
    representation left now the dense splice cache is retired — so
    the contract is pinned per family on the paged engine."""
    mdl, cfg = _tiny_cfg(family)
    vocab = cfg.vocab_size
    params = mdl.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8, paged=True).start()
    try:
        shared = [int(t) for t in jax.random.randint(
            jax.random.key(11), (17,), 1, vocab)]  # 2 full 8-chunks
        cold = engine.submit(shared + [5, 6], max_tokens=4)
        cold_toks = cold.result(timeout=300.0)
        warm = engine.submit(shared + [7, 8, 9], max_tokens=4)
        warm_toks = warm.result(timeout=300.0)

        for prompt, got in ((shared + [5, 6], cold_toks),
                            (shared + [7, 8, 9], warm_toks)):
            ref = mdl.decode(cfg, params, jnp.asarray([prompt]),
                             jnp.int32(len(prompt)), 4, len(prompt) + 4)
            assert got == [int(t) for t in ref[0]]
        assert cold.cached_prompt_tokens == 0
        assert warm.cached_prompt_tokens == 16
        assert warm.prefill_chunks < cold.prefill_chunks
        assert engine.prefix_cache.stats()["tokens_saved"] >= 16
    finally:
        engine.shutdown()


def test_prefix_hit_seeded_sampling_parity():
    """A temperature>0 stream is bit-identical warm vs cold: the hit
    restores the exact KV rows prefill would recompute, and the
    fold_in(seed, position) keys never see the cache. The cold
    baseline is the dense engine — which has NO prefix cache at all
    now the splice pool is retired — and the warm engine is the paged
    pool's always-on zero-copy trie."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    prompt = [int(t) for t in jax.random.randint(
        jax.random.key(3), (21,), 1, 128)]

    def run(engine_paged):
        engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                              prefill_chunk=8,
                              paged=engine_paged).start()
        try:
            # Sequential on purpose: the second submission must see the
            # first's published chunks (cache-hit path).
            first = engine.submit(prompt, max_tokens=6,
                                  temperature=0.9, seed=17)
            first_toks = first.result(timeout=300.0)
            second = engine.submit(prompt, max_tokens=6,
                                   temperature=0.9, seed=17)
            return first_toks, second.result(timeout=300.0), second
        finally:
            engine.shutdown()

    cold1, cold2, _ = run(engine_paged=False)
    warm1, warm2, warm_req = run(engine_paged=True)
    assert cold1 == cold2 == warm1 == warm2
    assert warm_req.cached_prompt_tokens > 0  # the hit really happened


# The dense splice cache (PrefixCache + _insert_chunk/_gather_chunk)
# is retired; its pool-level eviction contract lives on against the
# paged trie in test_paged_kv.py::
# test_paged_trie_lru_refcount_and_interior_protection.


def test_engine_slot_churn_respects_pool_budget_and_parity():
    """Slot churn through a SMALL block pool: every stream stays
    token-identical to the fixed path while trie eviction constantly
    recycles blocks (LRU + refcount safety under churn), and the pool
    accounting identity free + trie == usable holds after every
    request (engine driven step-by-step — no scheduler races)."""
    import random
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    # 9 usable 8-token blocks: one live request plus a couple of
    # cached chunks — publish-on-free forces constant eviction.
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8, paged=True,
                          kv_pool_blocks=10)
    rng = random.Random(2)
    for _ in range(6):
        prompt = [rng.randint(1, 127)
                  for _ in range(rng.randint(9, 20))]
        req = engine.submit(prompt, max_tokens=3)
        for _ in range(200):
            engine._admit()
            did = engine._prefill_one()
            did = engine._decode_step() or did
            if not did and not engine._waiting:
                break
        got = req.result(timeout=5.0)
        ref = llama.decode(cfg, params, jnp.asarray([prompt]),
                           jnp.int32(len(prompt)), 3,
                           len(prompt) + 3)
        assert got == [int(t) for t in ref[0]]
        pool = engine._pool
        assert pool.free_blocks() + len(engine.prefix_cache.nodes()) \
            == pool.usable_blocks


def test_cancel_mid_prefill_releases_block_refcounts():
    """A request cancelled between admission and prefill completion
    must unpin every trie node it aliased and return its own blocks —
    the pool accounting identity holds afterwards (engine driven
    step-by-step on this thread — no scheduler races)."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=1, max_seq=64,
                          prefill_chunk=8, paged=True)
    # NOT started: drive _admit/_prefill_one/_decode_step directly.
    shared = [int(t) for t in jax.random.randint(
        jax.random.key(5), (18,), 1, 128)]
    first = engine.submit(shared, max_tokens=1)
    engine._admit()
    for _ in range(8):
        if not engine._prefill_one():
            break
        engine._decode_step()
    assert first.result(timeout=5.0)          # finished + published
    assert engine.prefix_cache.stats()["chunks"] == 2

    second = engine.submit(shared + [3, 4, 5, 6, 7, 8, 9, 10, 11],
                           max_tokens=4)
    engine._admit()
    pinned = [n for n in engine.prefix_cache.nodes() if n.refs > 0]
    assert len(pinned) == 2                   # admission pinned the hit
    second.cancel()
    engine._prefill_one()                     # cancel path frees slot
    assert all(n.refs == 0 for n in engine.prefix_cache.nodes())
    pool = engine._pool
    assert pool.free_blocks() + len(engine.prefix_cache.nodes()) \
        == pool.usable_blocks
    assert pool._reserved == 0
    assert second.result(timeout=5.0) == []   # clean cancelled stream


def test_prefix_metrics_reach_replica_endpoint():
    """Hit/miss/tokens-saved counters and the split TTFT histogram are
    part of the replica's /metrics surface (and therefore of the LB's
    merged scrape) — emitted by the paged zero-copy trie, the only
    prefix-cache representation left. The quant info gauges ride the
    same surface (0 here: bf16 engine)."""
    from skypilot_tpu.observability import metrics as metrics_lib
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    saved_before = metrics_lib.REGISTRY.counter(
        "stpu_engine_prefill_tokens_saved_total").get()
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8, paged=True).start()
    try:
        shared = list(range(1, 18))
        engine.submit(shared, max_tokens=2).result(timeout=300.0)
        engine.submit(shared + [19], max_tokens=2).result(timeout=300.0)
    finally:
        engine.shutdown()
    assert metrics_lib.REGISTRY.counter(
        "stpu_engine_prefill_tokens_saved_total").get() >= \
        saved_before + 16
    text = metrics_lib.render()
    assert "stpu_engine_prefix_cache_hits_total" in text
    assert 'stpu_engine_prefix_ttft_seconds_count{cache="hit"}' in text
    assert "stpu_engine_kv_quant_enabled 0" in text
    assert "stpu_engine_weight_quant_enabled 0" in text


# ------------------------------------------------- prefix-affinity LB
def test_prefix_affinity_routes_equal_prefixes_together():
    """Equal-prefix requests land on ONE replica; when that replica
    disappears they remap consistently to a surviving replica; traffic
    without a prompt falls back to least-loaded."""
    from skypilot_tpu.serve.load_balancing_policies import \
        PrefixAffinityPolicy

    policy = PrefixAffinityPolicy()
    urls = [f"http://replica-{i}" for i in range(4)]
    policy.set_ready_replicas(urls)
    body = json.dumps({"prompt": list(range(100)),
                       "max_tokens": 4}).encode()
    req = {"path": "/generate", "body": body}

    def pick():
        url = policy.select_replica(req)
        policy.report_done(url)   # request completes -> load returns
        return url

    picks = {pick() for _ in range(8)}
    assert len(picks) == 1
    target = picks.pop()

    # Replica vanishes: every equal-prefix request remaps to the SAME
    # survivor (consistent hashing), never bounces.
    policy.set_ready_replicas([u for u in urls if u != target])
    remapped = {pick() for _ in range(8)}
    assert len(remapped) == 1 and target not in remapped

    # It comes back: affinity returns to the original owner.
    policy.set_ready_replicas(urls)
    assert pick() == target

    # DIFFERENT prefixes spread: with vnodes, 20 distinct prefixes on
    # 4 replicas never all hash to one arc.
    spread = {policy.select_replica({"path": "/generate",
                                     "body": json.dumps(
                                         {"prompt": [i] * 70}).encode()})
              for i in range(20)}
    assert len(spread) > 1


def test_prefix_affinity_bounded_load_spills_deterministically():
    """One dominant prefix must NOT pin the whole fleet's traffic on
    its owner: once the owner's in-flight count crosses the bounded-
    load threshold, requests spill to the ring successor (which then
    warms too) — and the spill target is deterministic, not random."""
    from skypilot_tpu.serve.load_balancing_policies import \
        PrefixAffinityPolicy

    policy = PrefixAffinityPolicy()
    policy.set_ready_replicas([f"http://replica-{i}" for i in range(4)])
    req = {"path": "/generate",
           "body": json.dumps({"prompt": list(range(100))}).encode()}
    # No report_done: every request stays in flight (slow decodes).
    picks = [policy.select_replica(req) for _ in range(8)]
    owner = picks[0]
    assert picks[1] == owner              # under the bound: affinity
    spilled = [u for u in picks if u != owner]
    assert spilled                        # over the bound: spill
    assert len(set(spilled)) == 1         # ... to ONE successor
    # Owner still carries the larger share (affinity preserved).
    assert picks.count(owner) >= len(spilled)


def test_prefix_affinity_fallback_least_loaded_and_report_done():
    from skypilot_tpu.serve.load_balancing_policies import \
        PrefixAffinityPolicy

    policy = PrefixAffinityPolicy()
    policy.set_ready_replicas(["http://a", "http://b"])
    body = json.dumps({"prompt": list(range(80))}).encode()
    busy = policy.select_replica({"path": "/generate", "body": body})
    other = "http://a" if busy == "http://b" else "http://b"
    # No prompt -> least loaded, i.e. NOT the replica holding the
    # in-flight generate.
    assert policy.select_replica({"path": "/health",
                                  "body": None}) == other
    policy.report_done(busy)
    policy.report_done(other)
    # Unknown url must not crash the accounting.
    policy.report_done("http://gone")


def test_lb_proxies_through_prefix_affinity_policy():
    """End to end through the real LB: the proxy hands the request body
    to the policy (content-aware selection) and returns the in-flight
    slot when the response completes."""
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import \
        PrefixAffinityPolicy

    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    lb = None
    try:
        assert ready.wait(timeout=300)
        policy = PrefixAffinityPolicy()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        policy.set_ready_replicas([url])
        lb = lb_lib.run_load_balancer(0, policy,
                                      lb_lib.RequestRecorder())
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_tokens": 3}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{lb.server_address[1]}/generate",
            data=body, headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert len(json.loads(resp.read())["tokens"]) == 3
        assert policy._inflight[url] == 0    # slot returned
    finally:
        if lb is not None:
            lb.shutdown()
        httpd.shutdown()


def test_engine_shutdown_fails_pending_requests():
    """shutdown() must not strand callers blocked on queues."""
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=1, max_seq=32,
                          prefill_chunk=8).start()
    engine.warmup()
    reqs = [engine.submit([1, 2], max_tokens=8) for _ in range(3)]
    engine.shutdown()
    for req in reqs:
        try:
            req.result(timeout=30.0)
        except EngineError:
            pass  # "engine shut down" is the expected outcome
    with pytest.raises(EngineError, match="shut down"):
        engine.submit([1], max_tokens=1)
