"""Continuous-batching decode engine + ragged KV-cache decode.

The contract under test, from strongest to weakest layer:

  * split-KV (flash-decode-style) attention == dense masked softmax;
  * batched decode with PER-EXAMPLE prompt lengths matches per-request
    sequential decode token-for-token (greedy) — batch composition
    must never change any row's tokens;
  * the engine (slot scheduling, chunked prefill interleaved with
    decode, slot reuse) reproduces the same tokens — including that
    stale K/V left in a reused slot is never attendable;
  * engine counters land in the observability registry and the replica
    /metrics surface.
"""
import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import gemma, llama, mixtral
from skypilot_tpu.observability import metrics
from skypilot_tpu.serve.decode_engine import DecodeEngine, EngineError


def _ragged_prompts(key, lens, vocab):
    return [jax.random.randint(jax.random.key(key + i), (l,), 1, vocab)
            for i, l in enumerate(lens)]


def _pad(prompts, s_pad):
    b = len(prompts)
    out = jnp.zeros((b, s_pad), jnp.int32)
    for i, p in enumerate(prompts):
        out = out.at[i, :p.shape[0]].set(p)
    return out


@pytest.mark.parametrize("seq_len,block", [(32, 4), (30, 8)])
def test_split_kv_matches_dense_reference(seq_len, block):
    """Blocked online-softmax over the ragged cache == one dense
    masked softmax, across block boundaries — including a cache length
    the block does NOT divide (the clamped-overlap tail window)."""
    B, T, KVH, G, D = 2, 3, 2, 2, 8
    q = jax.random.normal(jax.random.key(0), (B, T, KVH, G, D))
    ck = jax.random.normal(jax.random.key(1), (B, seq_len, KVH, D))
    cv = jax.random.normal(jax.random.key(2), (B, seq_len, KVH, D))
    positions = jnp.array([[18, 19, 20], [7, 8, 9]])
    valid = jnp.array([21, 10])

    out = llama._split_kv_attention(q, ck, cv, positions, valid,
                                    block=block)

    kpos = jnp.arange(seq_len)
    mask = ((kpos[None, None, :] <= positions[..., None]) &
            (kpos[None, None, :] < valid[:, None, None]))
    scores = jnp.einsum("btkgd,bskd->bkgts", q, ck) * (D ** -0.5)
    scores = jnp.where(mask[:, None, None], scores, -1e30)
    dense = jnp.einsum("bkgts,bskd->btkgd",
                       jax.nn.softmax(scores, axis=-1), cv)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)


def test_ragged_batched_decode_matches_sequential():
    """One batched decode over heterogeneous prompt lengths must equal
    per-request decode token-for-token — the property the fixed-batch
    path enforced by REJECTING (B,) lengths."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    lens, mt, s_pad = [3, 7, 5], 6, 8
    prompts = _ragged_prompts(1, lens, 128)

    got = llama.decode(cfg, params, _pad(prompts, s_pad),
                       jnp.asarray(lens), mt, s_pad + mt)
    for i, p in enumerate(prompts):
        ref = llama.decode(cfg, params, p[None, :], jnp.int32(lens[i]),
                           mt, lens[i] + mt)
        np.testing.assert_array_equal(np.asarray(got[i]),
                                      np.asarray(ref[0]))


@pytest.mark.parametrize("family", ["mixtral", "gemma"])
def test_ragged_decode_other_families(family):
    """The (B,) length contract holds through the shared loop for the
    MoE (dense-routed) and MQA/tied-head families too."""
    mdl = {"mixtral": mixtral, "gemma": gemma}[family]
    cfg = mdl.MixtralConfig.tiny() if family == "mixtral" \
        else mdl.GemmaConfig.tiny(vocab_size=128)
    vocab = cfg.vocab_size
    params = mdl.init(cfg, jax.random.key(0))
    lens, mt, s_pad = [2, 5], 4, 6
    prompts = _ragged_prompts(3, lens, vocab)

    got = mdl.decode(cfg, params, _pad(prompts, s_pad),
                     jnp.asarray(lens), mt, s_pad + mt)
    for i, p in enumerate(prompts):
        ref = mdl.decode(cfg, params, p[None, :], jnp.int32(lens[i]),
                         mt, lens[i] + mt)
        np.testing.assert_array_equal(np.asarray(got[i]),
                                      np.asarray(ref[0]))


def test_decode_rejects_mismatched_length_vector():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init(cfg, jax.random.key(0))
    prompt = jnp.ones((2, 4), jnp.int32)
    with pytest.raises(ValueError, match="scalar or a"):
        llama.decode(cfg, params, prompt, jnp.asarray([1, 2, 3]), 2, 16)


def test_decode_with_donated_preallocated_cache():
    """The caller-allocated-and-donated cache path (bench + serving)
    produces the same tokens as the internal-allocation path, and the
    donation is actually USABLE (return_cache=True puts the cache in
    the jit output, so XLA can alias the donated input to it)."""
    import warnings
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init(cfg, jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 1, 64)
    ref = llama.decode(cfg, params, prompt, jnp.int32(5), 4, 16)

    decode_jit = jax.jit(
        lambda p, pr, cache: llama.decode(cfg, p, pr, jnp.int32(5), 4,
                                          16, cache=cache,
                                          return_cache=True),
        donate_argnums=(2,))
    with warnings.catch_warnings():
        warnings.filterwarnings(
            "error", message=".*donated buffers were not usable.*")
        got, _ = decode_jit(params, prompt, llama.init_cache(cfg, 2, 16))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_engine_matches_decode_across_slot_reuse():
    """5 ragged greedy requests through 2 slots: every request's
    stream must equal its own fixed-path decode — requests 3..5 reuse
    slots whose rows still hold the previous request's K/V, so any
    leak of stale (masked) cache into attention breaks this."""
    import random
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8).start()
    try:
        rng = random.Random(0)
        specs = [([rng.randint(1, 127)
                   for _ in range(rng.randint(1, 19))],
                  rng.randint(1, 8)) for _ in range(5)]
        reqs = [engine.submit(p, max_tokens=mt) for p, mt in specs]
        for (p, mt), req in zip(specs, reqs):
            got = req.result(timeout=300.0)
            ref = llama.decode(cfg, params,
                               jnp.asarray([p], jnp.int32),
                               jnp.int32(len(p)), mt, len(p) + mt)
            assert got == [int(t) for t in ref[0]], (p, mt)
    finally:
        engine.shutdown()


def test_engine_chunked_prefill_long_prompt():
    """A prompt spanning several prefill chunks (chunk 8, prompt 19)
    must decode identically to the single-pass prefill path."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8).start()
    try:
        prompt = [int(t) for t in jax.random.randint(
            jax.random.key(7), (19,), 1, 128)]
        got = engine.submit(prompt, max_tokens=6).result(timeout=300.0)
        ref = llama.decode(cfg, params, jnp.asarray([prompt]),
                           jnp.int32(19), 6, 32)
        assert got == [int(t) for t in ref[0]]
    finally:
        engine.shutdown()


def test_engine_sampling_reproducible_and_limits():
    """Seeded sampling is slot- and batch-composition-independent;
    oversized and empty requests are rejected upfront."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=2, max_seq=32,
                          prefill_chunk=8).start()
    try:
        r1 = engine.submit([5, 6, 7], max_tokens=5, temperature=0.8,
                           seed=42).result(timeout=300.0)
        # Second run shares the batch with another live request — the
        # fold_in(seed, position) keys must not notice.
        other = engine.submit([9, 9, 9, 9], max_tokens=8)
        r2 = engine.submit([5, 6, 7], max_tokens=5, temperature=0.8,
                           seed=42).result(timeout=300.0)
        other.result(timeout=300.0)
        assert r1 == r2
        with pytest.raises(EngineError, match="exceeds"):
            engine.submit(list(range(1, 30)), max_tokens=16)
        with pytest.raises(EngineError, match="empty"):
            engine.submit([], max_tokens=4)
    finally:
        engine.shutdown()


def test_engine_metrics_in_registry_and_replica_endpoint():
    """Slot/queue gauges and token/TTFT series reach the process
    registry, and the replica serves them on GET /metrics."""
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    tokens_before = metrics.REGISTRY.counter(
        "stpu_engine_decode_tokens_total").get()

    from skypilot_tpu.recipes import serve_llm
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=300)
        port = httpd.server_address[1]
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_tokens": 4}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert len(json.loads(resp.read())["tokens"]) == 4
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        assert "stpu_engine_slots_total 2" in text
        assert "stpu_engine_queue_depth" in text
        assert "stpu_engine_ttft_seconds_count" in text
        assert metrics.REGISTRY.counter(
            "stpu_engine_decode_tokens_total").get() >= tokens_before + 4
    finally:
        httpd.shutdown()


def test_lb_metrics_include_replica_engine_families():
    """The LB /metrics snapshot merges each ready replica's exposition
    (engine slot/queue/token families) into one scrape."""
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import \
        RoundRobinPolicy

    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=2)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    lb = None
    try:
        assert ready.wait(timeout=300)
        policy = RoundRobinPolicy()
        policy.set_ready_replicas(
            [f"http://127.0.0.1:{httpd.server_address[1]}"])
        lb = lb_lib.run_load_balancer(0, policy,
                                      lb_lib.RequestRecorder())
        with urllib.request.urlopen(
                f"http://127.0.0.1:{lb.server_address[1]}/metrics",
                timeout=30) as resp:
            text = resp.read().decode()
        assert "stpu_lb_requests_total" in text       # LB's own
        assert "stpu_engine_slots_total" in text      # replica's
    finally:
        if lb is not None:
            lb.shutdown()
        httpd.shutdown()


def test_serve_llm_legacy_path_still_serves():
    """engine_slots=0 keeps the locked fixed-batch path working (the
    comparability baseline), including its donated-cache _decode."""
    from skypilot_tpu.recipes import serve_llm
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready,
                            engine_slots=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=300)
        assert httpd.engine is None
        port = httpd.server_address[1]
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_tokens": 6}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            toks = json.loads(resp.read())["tokens"]
        ref = llama.decode(cfg, params, jnp.asarray([[1, 2, 3]]),
                           jnp.int32(3), 6, 128)
        assert toks == [int(t) for t in ref[0]][:6]
    finally:
        httpd.shutdown()


def test_engine_shutdown_fails_pending_requests():
    """shutdown() must not strand callers blocked on queues."""
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    params = llama.init(cfg, jax.random.key(0))
    engine = DecodeEngine(cfg, params, slots=1, max_seq=32,
                          prefill_chunk=8).start()
    engine.warmup()
    reqs = [engine.submit([1, 2], max_tokens=8) for _ in range(3)]
    engine.shutdown()
    for req in reqs:
        try:
            req.result(timeout=30.0)
        except EngineError:
            pass  # "engine shut down" is the expected outcome
    with pytest.raises(EngineError, match="shut down"):
        engine.submit([1], max_tokens=1)
