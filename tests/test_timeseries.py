"""Fleet time-series store (observability/timeseries.py).

The contract under test: two-tier downsampling (raw points fold into
(count, sum, min, max) rollup buckets as they age out), one point per
raw bucket (a fast collector overwrites in place instead of growing
the ring), counter window_delta with reset clamping, and cumulative
histogram snapshots whose window delta merges bucket-wise across
series and survives a replica restart with changed bounds.
"""
import math

import pytest

from skypilot_tpu.observability.promtext import HistogramSnapshot
from skypilot_tpu.observability.timeseries import TimeSeriesStore


def _store(**kw):
    defaults = dict(raw_seconds=10.0, raw_retention=60.0,
                    rollup_seconds=30.0, rollup_retention=600.0)
    defaults.update(kw)
    return TimeSeriesStore(**defaults)


def _snap(counts, bounds=(0.1, 1.0)):
    """Cumulative snapshot from per-bucket counts (incl. +Inf)."""
    cum, total = [], 0.0
    for c in counts:
        total += c
        cum.append(total)
    return HistogramSnapshot(bounds=list(bounds), cumulative=cum,
                             sum=float(total), count=total)


# ------------------------------------------------------------- scalars
def test_one_point_per_raw_bucket_overwrites_in_place():
    store = _store()
    for i in range(5):
        store.record("g", float(i), ts=100.0 + i)   # < raw_seconds apart
    pts = store.points("g")
    assert pts == [(100.0, 4.0)]                    # newest value wins
    store.record("g", 9.0, ts=111.0)                # next raw bucket
    assert store.points("g") == [(100.0, 4.0), (111.0, 9.0)]
    assert store.latest("g") == 9.0


def test_downsample_folds_raw_into_rollup_means():
    store = _store()
    # Points at t=0,10,20 (values 1,2,3) age out when t reaches 100
    # (raw_retention=60): they fold into the t=0 rollup bucket
    # (rollup_seconds=30 → floor(ts/30)*30 = 0 for all three).
    for ts, v in ((0.0, 1.0), (10.0, 2.0), (20.0, 3.0)):
        store.record("g", v, ts=ts)
    store.record("g", 7.0, ts=100.0)
    pts = store.points("g")
    assert pts == [(0.0, 2.0), (100.0, 7.0)]        # rollup mean = 2.0
    # min/max survive inside the bucket (spikes aren't averaged away):
    series = next(iter(store._scalars.values()))
    assert (series.rollup[0].min, series.rollup[0].max) == (1.0, 3.0)


def test_rollup_retention_drops_ancient_buckets():
    store = _store(raw_retention=10.0, rollup_retention=180.0)
    store.record("g", 1.0, ts=0.0)
    store.record("g", 2.0, ts=50.0)
    # At t=200 the t=0 rollup bucket is > 180s old: dropped. The t=50
    # point folded into bucket ts=30 (floor(50/30)*30), which survives.
    store.record("g", 3.0, ts=200.0)
    assert [t for t, _ in store.points("g")] == [30.0, 200.0]


def test_nan_points_dropped_at_the_door():
    store = _store()
    store.record("g", float("nan"), ts=0.0)
    assert store.points("g") == []
    assert store.latest("g") is None


def test_latest_sums_across_matching_label_sets():
    store = _store()
    store.record("c", 3.0, ts=0.0, code="200")
    store.record("c", 2.0, ts=0.0, code="500")
    assert store.latest("c") == 5.0
    assert store.latest("c", code="500") == 2.0
    assert store.latest("c", code="404") is None
    assert store.labels_for("c") == [{"code": "200"}, {"code": "500"}]
    assert store.series_names() == ["c"]


# ------------------------------------------------------------ counters
def test_window_delta_baseline_at_window_start():
    store = _store(raw_seconds=1.0, raw_retention=1000.0)
    for ts, total in ((0.0, 10.0), (10.0, 40.0), (20.0, 100.0)):
        store.record("c", total, ts=ts)
    # Window [5, 20]: baseline = newest point <= 5 → t=0 (10.0).
    assert store.window_delta("c", 15.0, now=20.0) == 90.0
    # Short history: window opens before the oldest point → oldest.
    assert store.window_delta("c", 500.0, now=20.0) == 90.0
    assert store.window_delta("c", 15.0, now=20.0, code="x") is None
    assert store.rate("c", 15.0, now=20.0) == pytest.approx(6.0)


def test_window_delta_clamps_counter_reset():
    """A restarted replica's counter drops to near zero; the delta
    clamps to the post-reset total instead of going negative."""
    store = _store(raw_seconds=1.0, raw_retention=1000.0)
    store.record("c", 100.0, ts=0.0)
    store.record("c", 5.0, ts=10.0)     # reset: 100 → 5
    assert store.window_delta("c", 20.0, now=10.0) == 5.0


def test_window_delta_uses_rollup_max_for_aged_counters():
    """A counter point that aged into a rollup bucket contributes its
    bucket MAX as the baseline (the counter total at bucket close),
    not the mean — a mean baseline would overstate the delta."""
    store = _store(raw_seconds=1.0, raw_retention=50.0,
                   rollup_seconds=30.0)
    for ts, total in ((0.0, 10.0), (10.0, 20.0), (20.0, 30.0)):
        store.record("c", total, ts=ts)
    store.record("c", 90.0, ts=100.0)   # ages the first three out
    # Window [60, 100]: baseline = rollup bucket t=0 with max=30.
    assert store.window_delta("c", 40.0, now=100.0) == 60.0


# ---------------------------------------------------------- histograms
def test_histogram_delta_is_window_distribution():
    store = _store(raw_seconds=1.0, raw_retention=1000.0)
    store.record_histogram("h", _snap([5, 0, 0]), ts=0.0)
    store.record_histogram("h", _snap([5, 10, 0]), ts=30.0)
    delta = store.histogram_delta("h", window=20.0, now=30.0)
    assert delta.count == 10            # only the window's observations
    assert delta.cumulative == [0.0, 10.0, 10.0]
    assert 0.1 <= delta.quantile(0.5) <= 1.0


def test_histogram_delta_merges_equal_bounds_across_series():
    store = _store(raw_seconds=1.0, raw_retention=1000.0)
    store.record_histogram("h", _snap([0, 0, 0]), ts=0.0, replica="a")
    store.record_histogram("h", _snap([0, 0, 0]), ts=0.0, replica="b")
    store.record_histogram("h", _snap([2, 0, 0]), ts=30.0, replica="a")
    store.record_histogram("h", _snap([0, 3, 0]), ts=30.0, replica="b")
    merged = store.histogram_delta("h", window=100.0, now=30.0)
    assert merged.count == 5
    assert merged.cumulative == [2.0, 5.0, 5.0]


def test_histogram_delta_skips_series_with_changed_bounds():
    """A replica restart with a different bucket layout makes the
    delta undefined for that series — it is skipped, not fabricated."""
    store = _store(raw_seconds=1.0, raw_retention=1000.0)
    store.record_histogram("h", _snap([5, 0, 0]), ts=0.0, replica="a")
    store.record_histogram("h", _snap([5, 1, 0], bounds=(0.5, 2.0)),
                           ts=30.0, replica="a")
    assert store.histogram_delta("h", window=20.0, now=30.0) is None
    assert store.histogram_delta("h", 20.0, now=30.0, replica="x") is None


def test_histogram_snapshots_thin_to_one_per_rollup_bucket():
    store = _store(raw_seconds=1.0, raw_retention=10.0,
                   rollup_seconds=30.0)
    for i in range(20):                 # t=0..19, all older than t=100-10
        store.record_histogram("h", _snap([i, 0, 0]), ts=float(i))
    store.record_histogram("h", _snap([50, 0, 0]), ts=100.0)
    series = next(iter(store._hists.values()))
    # One survivor per rollup bucket (t=0 bucket) + the raw point.
    assert len(series.snaps) == 2
    # The newest snapshot within the bucket won (cumulative counts
    # make the latest the most informative).
    assert series.snaps[0][1].count == 19


def test_empty_window_delta_has_zero_count_not_nan():
    """The satellite-3 substrate: a window with no new observations
    deltas to count == 0 and quantile NaN — the SLO monitor and CLI
    must map this to None/'-', never compare NaN to a threshold."""
    store = _store(raw_seconds=1.0, raw_retention=1000.0)
    store.record_histogram("h", _snap([5, 0, 0]), ts=0.0)
    store.record_histogram("h", _snap([5, 0, 0]), ts=30.0)
    delta = store.histogram_delta("h", window=20.0, now=30.0)
    assert delta.count == 0
    assert math.isnan(delta.quantile(0.99))


def test_to_doc_shape():
    store = _store()
    store.record("g", 1.0, ts=0.0, replica="a")
    doc = store.to_doc("g")
    assert doc == {"series": "g",
                   "data": [{"labels": {"replica": "a"},
                             "points": [(0.0, 1.0)]}]}
