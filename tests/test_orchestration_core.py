"""Hermetic tests for catalog / Resources / Task / Dag / Optimizer.

Mirrors the reference's dryrun test strategy (tests/test_optimizer_dryruns.py
e.g. test_partial_tpu:134, test_invalid_cloud_tpu:147): no credentials, the
static catalog is the world.
"""
import textwrap

import pytest

from skypilot_tpu import catalog, exceptions, optimizer
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


# --------------------------------------------------------------- catalog

def test_slice_info_topology_math():
    info = catalog.slice_info("tpu-v5p-64")
    assert info.chips == 32
    assert info.hosts == 8          # v5p: 4 chips/host
    assert info.cores == 64
    assert info.is_pod

    v5e = catalog.slice_info("tpu-v5e-16")
    assert v5e.chips == 16 and v5e.hosts == 2   # v5e: 8 chips/host

    single = catalog.slice_info("tpu-v5e-8")
    assert single.hosts == 1 and not single.is_pod

    v6e = catalog.slice_info("tpu-v6e-16")
    assert v6e.hosts == 4           # v6e: 4 chips/host


def test_unknown_slice_has_helpful_error():
    with pytest.raises(ValueError, match="Known v5p slices"):
        catalog.slice_info("tpu-v5p-48")
    with pytest.raises(ValueError, match="tpu-<gen>-<size>"):
        catalog.slice_info("a100-8")


def test_spot_cheaper_than_ondemand():
    od = catalog.tpu_price("tpu-v5e-16", use_spot=False)
    spot = catalog.tpu_price("tpu-v5e-16", use_spot=True)
    assert spot < od


def test_list_accelerators_filter():
    rows = catalog.list_accelerators(name_filter="v5p-8$")
    assert rows and all(r["accelerator"] == "tpu-v5p-8" for r in rows)


def test_egress_cost_model():
    assert catalog.egress_cost_per_gb("us-central1", "us-central1") == 0.0
    assert catalog.egress_cost_per_gb("us-central1", "us-east5") > 0
    assert (catalog.egress_cost_per_gb("us-central1", "europe-west4") >
            catalog.egress_cost_per_gb("us-central1", "us-east5"))


# ------------------------------------------------------------- resources

def test_resources_validation():
    r = Resources(accelerator="tpu-v5e-16", zone="us-west4-a")
    assert r.region == "us-west4"
    assert r.num_hosts == 2
    assert r.is_launchable

    with pytest.raises(exceptions.InvalidTaskError, match="not offered"):
        Resources(accelerator="tpu-v4-8", region="us-west4")

    with pytest.raises(exceptions.InvalidTaskError,
                       match="mutually exclusive"):
        Resources(accelerator="tpu-v5e-8", instance_type="n2-standard-8")

    with pytest.raises(exceptions.InvalidTaskError):
        Resources(accelerator="nvidia-a100")


def test_resources_from_yaml_count_must_be_one():
    with pytest.raises(exceptions.InvalidTaskError, match="bigger"):
        Resources.from_yaml_config({"accelerators": {"tpu-v5e-8": 4}})
    r = Resources.from_yaml_config({"accelerators": {"tpu-v5e-8": 1}})
    assert r.accelerator == "tpu-v5e-8"


def test_resources_pricing_and_spot_cleanup():
    spot = Resources(accelerator="tpu-v5e-16", use_spot=True)
    od = Resources(accelerator="tpu-v5e-16")
    assert spot.hourly_price() < od.hourly_price()
    assert spot.need_cleanup_after_preemption()
    assert not od.need_cleanup_after_preemption()
    assert od.get_cost(3600) == pytest.approx(od.hourly_price())


def test_resources_runtime_version_defaults():
    assert Resources(accelerator="tpu-v5p-8").tpu_runtime_version == \
        "v2-alpha-tpuv5"
    assert Resources(accelerator="tpu-v5e-8",
                     runtime_version="custom").tpu_runtime_version == \
        "custom"


def test_less_demanding_than():
    want = Resources(accelerator="tpu-v5e-16")
    have = Resources(accelerator="tpu-v5e-16", zone="us-west4-a")
    assert want.less_demanding_than(have)
    assert not Resources(accelerator="tpu-v5e-32").less_demanding_than(have)
    assert not Resources(accelerator="tpu-v5e-16",
                         use_spot=True).less_demanding_than(have)


def test_resources_yaml_roundtrip():
    r = Resources(accelerator="tpu-v5p-32", region="us-east5",
                  use_spot=True, ports=("8888",))
    r2 = Resources.from_yaml_config(r.to_yaml_config())
    assert r2 == r


# ------------------------------------------------------------ task / dag

def test_task_from_yaml(tmp_path):
    yaml_path = tmp_path / "task.yaml"
    yaml_path.write_text(textwrap.dedent("""\
        name: train
        resources:
          accelerators: tpu-v5e-16
          use_spot: true
        num_nodes: 2
        envs:
          MODEL: llama3
        setup: pip install -e .
        run: python train.py --model $MODEL
        """))
    task = Task.from_yaml(str(yaml_path))
    assert task.name == "train"
    assert task.num_nodes == 2
    assert task.resources[0].accelerator == "tpu-v5e-16"
    assert task.resources[0].use_spot
    assert task.envs["MODEL"] == "llama3"
    # Round-trip.
    task2 = Task.from_yaml_config(task.to_yaml_config())
    assert task2.to_yaml_config() == task.to_yaml_config()


def test_task_yaml_rejects_unknown_fields():
    with pytest.raises(exceptions.InvalidTaskError, match="run_cmd"):
        Task.from_yaml_config({"run_cmd": "echo hi"})


def test_task_env_none_requires_override():
    cfg = {"envs": {"HF_TOKEN": None}, "run": "echo $HF_TOKEN"}
    with pytest.raises(exceptions.InvalidTaskError, match="HF_TOKEN"):
        Task.from_yaml_config(cfg)
    task = Task.from_yaml_config(cfg, env_overrides={"HF_TOKEN": "x"})
    assert task.envs["HF_TOKEN"] == "x"


def test_task_any_of_resources():
    task = Task.from_yaml_config({
        "resources": {
            "use_spot": True,
            "any_of": [{"accelerators": "tpu-v5e-16"},
                       {"accelerators": "tpu-v6e-16"}],
        },
        "run": "echo hi",
    })
    assert len(task.resources) == 2
    assert all(r.use_spot for r in task.resources)


def test_dag_chain_and_cycle():
    with Dag() as d:
        a = Task("a", run="echo a")
        b = Task("b", run="echo b")
        c = Task("c", run="echo c")
        a >> b >> c
    assert d.is_chain()
    assert [t.name for t in d.topo_order()] == ["a", "b", "c"]

    with Dag() as d2:
        x = Task("x")
        y = Task("y")
        z = Task("z")
        x >> z
        y >> z
    assert not d2.is_chain()
    assert [t.name for t in d2.topo_order()][-1] == "z"

    d2.add_edge(z, x)
    with pytest.raises(exceptions.DagError, match="cycle"):
        d2.topo_order()


# ---------------------------------------------------------------------
# All tests in this module isolate client state: the enabled-clouds set
# lives in the state DB, and a developer's real ~/.stpu (e.g. after
# `stpu check` on a machine where only `local` is usable) must not
# change optimizer planning or cluster bookkeeping.

@pytest.fixture(autouse=True)
def _isolated_state(tmp_state_dir):
    pass


def _single_task_dag(**task_kw):
    with Dag() as d:
        t = Task("t", run="echo hi", **task_kw)
    return d, t


def test_optimizer_picks_cheapest_zone():
    d, t = _single_task_dag()
    t.set_resources(Resources(accelerator="tpu-v5e-16"))
    optimizer.Optimizer.optimize(d, quiet=True)
    best = t.best_resources
    assert best.is_launchable
    # us-* zones have the 1.0 price multiplier -> must win over eu/asia.
    assert best.zone.startswith("us-")


def test_optimizer_respects_blocklist_and_exhaustion():
    d, t = _single_task_dag()
    t.set_resources(Resources(accelerator="tpu-v4-8"))  # only us-central2-b
    bl = optimizer.Blocklist().add("tpu-v4-8", "us-central2-b")
    with pytest.raises(exceptions.ResourcesUnavailableError):
        optimizer.Optimizer.optimize(d, blocklist=bl, quiet=True)


def test_optimizer_any_of_picks_cheaper_option():
    d, t = _single_task_dag()
    t.set_resources((Resources(accelerator="tpu-v5e-16", use_spot=True),
                     Resources(accelerator="tpu-v5p-32", use_spot=True)))
    optimizer.Optimizer.optimize(d, quiet=True)
    assert t.best_resources.accelerator == "tpu-v5e-16"


def test_optimizer_num_nodes_scales_cost():
    d1, t1 = _single_task_dag(num_nodes=1)
    t1.set_resources(Resources(accelerator="tpu-v5e-8"))
    d2, t2 = _single_task_dag(num_nodes=4)
    t2.set_resources(Resources(accelerator="tpu-v5e-8"))
    c1 = optimizer.launchable_candidates(t1)[0].hourly_price
    c2 = optimizer.launchable_candidates(t2)[0].hourly_price
    assert c2 == pytest.approx(4 * c1)


def test_optimizer_chain_egress_keeps_same_region():
    with Dag() as d:
        a = Task("producer", run="make data")
        b = Task("consumer", run="train")
        a >> b
    # Producer only exists in europe-west4: v3 in europe + us-central1.
    a.set_resources(Resources(accelerator="tpu-v3-8",
                              region="europe-west4"))
    a.estimated_output_gb = 10000.0  # huge egress penalty
    b.set_resources(Resources(accelerator="tpu-v2-8"))
    optimizer.Optimizer.optimize(d, quiet=True)
    # v2 is offered in europe-west4-a; egress should dominate the ~10%
    # regional price premium and keep the consumer in europe.
    assert b.best_resources.region == "europe-west4"

    # Without egress, the consumer goes to the cheaper us region.
    a.estimated_output_gb = 0.0
    optimizer.Optimizer.optimize(d, quiet=True)
    assert b.best_resources.region.startswith("us-")


def test_optimizer_time_vs_cost_target():
    d, t = _single_task_dag()
    t.set_resources((Resources(accelerator="tpu-v5e-16"),
                     Resources(accelerator="tpu-v5p-64")))
    # Bigger slice is 4x faster but much more expensive.
    t.set_time_estimator(
        lambda r: 900.0 if r.accelerator == "tpu-v5p-64" else 3600.0)
    optimizer.Optimizer.optimize(
        d, minimize=optimizer.OptimizeTarget.COST, quiet=True)
    assert t.best_resources.accelerator == "tpu-v5e-16"
    optimizer.Optimizer.optimize(
        d, minimize=optimizer.OptimizeTarget.TIME, quiet=True)
    assert t.best_resources.accelerator == "tpu-v5p-64"


def test_sync_runs_hosts_concurrently_and_aggregates_failures():
    """VERDICT r3 weak #3: workdir/file-mount sync fans out across
    hosts (serial rsync multiplied launch latency by host count);
    failures from ALL hosts are aggregated, not just the first."""
    import threading

    from skypilot_tpu import exceptions as exc
    from skypilot_tpu.backends import slice_backend

    n = 4
    barrier = threading.Barrier(n, timeout=10)

    class BarrierRunner:
        def __init__(self, i):
            self.node_id = f"h{i}"

        def rsync(self, *a, **kw):
            # Deadlocks (Barrier timeout -> BrokenBarrierError) unless
            # all hosts sync at the same time.
            barrier.wait()

    class Handle:
        def get_command_runners(self):
            return [BarrierRunner(i) for i in range(n)]

    backend = slice_backend.SliceBackend()
    backend._sync_workdir(Handle(), ".")  # no exception = concurrent

    class FailRunner:
        def __init__(self, i):
            self.node_id = f"h{i}"
            self.i = i

        def rsync(self, *a, **kw):
            if self.i != 0:
                raise RuntimeError(f"disk full on h{self.i}")

    class FailHandle:
        def get_command_runners(self):
            return [FailRunner(i) for i in range(3)]

    with pytest.raises(exc.CommandError) as ei:
        backend._sync_workdir(FailHandle(), ".")
    msg = str(ei.value)
    assert "2 host(s)" in msg and "h1" in msg and "h2" in msg


def test_catalog_ttl_refresh(tmp_path, monkeypatch):
    """`catalog.refresh_hours`: older CSV -> fetcher runs before
    pricing; fresh CSV -> no fetch; fetch failure -> warning + stale
    prices still served (VERDICT r4 next #9)."""
    import time as time_lib

    from skypilot_tpu import config as config_lib

    calls = []

    def fake_fetch_main():
        calls.append(1)

    from skypilot_tpu.catalog.data_fetchers import fetch_gcp_tpu
    monkeypatch.setattr(fetch_gcp_tpu, "main", fake_fetch_main)
    monkeypatch.setattr(config_lib, "get_nested",
                        lambda keys, default=None:
                        24 if keys == ("catalog", "refresh_hours")
                        else default)

    csv_mtime = (catalog._DATA_DIR / "gcp_tpus.csv").stat().st_mtime

    # Fresh CSV (now): no fetch.
    monkeypatch.setattr(catalog, "_refresh_checked", False)
    monkeypatch.setattr(time_lib, "time", lambda: csv_mtime + 3600)
    catalog._tpu_df.cache_clear()
    catalog.tpu_price("tpu-v5e-8")
    assert calls == []

    # Faked clock 48h past the CSV mtime: fetcher runs (once).
    monkeypatch.setattr(catalog, "_refresh_checked", False)
    monkeypatch.setattr(time_lib, "time",
                        lambda: csv_mtime + 48 * 3600)
    catalog._tpu_df.cache_clear()
    catalog.tpu_price("tpu-v5e-8")
    catalog.tpu_price("tpu-v5e-8")  # same process: checked once
    assert calls == [1]

    # Fetch failure: warning, stale price still served.
    def broken_fetch():
        raise RuntimeError("no network")
    monkeypatch.setattr(fetch_gcp_tpu, "main", broken_fetch)
    monkeypatch.setattr(catalog, "_refresh_checked", False)
    catalog._tpu_df.cache_clear()
    assert catalog.tpu_price("tpu-v5e-8") > 0

    monkeypatch.setattr(catalog, "_refresh_checked", False)
    catalog._tpu_df.cache_clear()
    catalog._vm_df.cache_clear()
