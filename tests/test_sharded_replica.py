"""Multi-host sharded serving: one replica = one gang-scheduled slice.

The contract under test (ISSUE 8 acceptance), strongest first:

  * a 2-process gang replica (self-spawned followers on the forced
    CPU mesh) serves end-to-end through LB → host 0 → TP engine with
    BIT-IDENTICAL greedy output and seeded-sampling parity vs the
    single-process engine; killing the follower mid-stream flips
    /health to 503, the whole-gang supervisor restart recovers, the
    next request through the LB succeeds, and the whole story is
    traced as ONE tree (lb.request → replica.generate → gang.run);
  * the serving instantiation of parallel/mesh.py resolves: TP-sharded
    KV cache specs for all 3 families (with the kv_heads divisibility
    fallback) and donation preserved through the sharded jitted
    decode/prefill entry points — a dropped donation silently doubles
    the KV cache in HBM;
  * topology plumbing: schema validation, spec round-trip, the replica
    manager gang-launching all hosts as ONE replica (num_nodes + env),
    the stpu_replica_topology_info gauge, and loadgen report
    attribution;
  * (the serve/ collectives lint now lives in tests/test_static_analysis.py).
"""
import dataclasses
import importlib.util
import json
import os
import pathlib
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.models import gemma, llama, mixtral
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.serve import decode_engine
from skypilot_tpu.serve import gang_replica
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.utils import schemas

REPO = pathlib.Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _get_code(url, timeout=10):
    try:
        return _get(url, timeout=timeout)[0]
    except urllib.error.HTTPError as e:
        return e.code
    except (urllib.error.URLError, ConnectionError, OSError):
        return None


def _post_json(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


# ================================================ topology spec plumbing
def test_replica_topology_schema_and_semantics():
    ok = {"readiness_probe": "/health",
          "replica_topology": {"hosts": 2, "ici_axes": {"tp": 2}}}
    schemas.validate_service(ok)
    spec = SkyServiceSpec.from_yaml_config(ok)
    assert spec.replica_topology == {"hosts": 2,
                                     "ici_axes": {"tp": 2}}
    topo = gang_replica.ReplicaTopology.from_config(
        spec.replica_topology)
    assert (topo.hosts, topo.tp, topo.label()) == (2, 2, "2x2")

    with pytest.raises(exceptions.InvalidTaskError):
        schemas.validate_service(
            {"readiness_probe": "/",
             "replica_topology": {"hosts": 0}})
    with pytest.raises(exceptions.InvalidTaskError):
        schemas.validate_service(
            {"readiness_probe": "/",
             "replica_topology": {"hosts": 2, "slices": 1}})
    with pytest.raises(exceptions.InvalidTaskError):
        schemas.validate_service(
            {"readiness_probe": "/",
             "replica_topology": {"ici_axes": {"tp": 2}}})
    with pytest.raises(exceptions.InvalidTaskError):
        # Schema-legal shape, semantically bad axis size.
        SkyServiceSpec.from_yaml_config(
            {"readiness_probe": "/",
             "replica_topology": {"hosts": 2,
                                  "ici_axes": {"tp": 0}}})


def test_replica_topology_yaml_roundtrip():
    spec = SkyServiceSpec.from_yaml_config(
        {"readiness_probe": "/health",
         "replicas": 1,
         "replica_topology": {"hosts": 2, "ici_axes": {"tp": 4}}})
    again = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert again.replica_topology == spec.replica_topology
    # Unsharded specs don't grow a topology block.
    plain = SkyServiceSpec(readiness_path="/")
    assert "replica_topology" not in plain.to_yaml_config()


def test_topology_env_roundtrip(monkeypatch):
    topo = gang_replica.ReplicaTopology(hosts=2, ici_axes={"tp": 2})
    monkeypatch.setenv(gang_replica.TOPOLOGY_ENV, topo.to_env_json())
    assert gang_replica.ReplicaTopology.from_env() == topo
    monkeypatch.setenv(gang_replica.TOPOLOGY_ENV, "{not json")
    with pytest.raises(gang_replica.GangError):
        gang_replica.ReplicaTopology.from_env()


@pytest.mark.usefixtures("tmp_state_dir")
def test_replica_manager_gang_launches_all_hosts(monkeypatch):
    """A topology-bearing spec launches the replica as ONE gang: the
    task copy carries num_nodes = hosts and the topology env, and the
    controller/LB still see exactly one replica."""
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.serve import replica_managers
    from skypilot_tpu.task import Task

    task = Task("tp-svc", run="python -m skypilot_tpu.recipes.serve_llm"
                              " --port $SKYPILOT_SERVE_REPLICA_PORT")
    task.set_resources(Resources(cloud="local"))
    task.service = SkyServiceSpec(
        readiness_path="/health", min_replicas=1,
        replica_topology={"hosts": 2, "ici_axes": {"tp": 2}})
    mgr = replica_managers.SkyPilotReplicaManager(
        "tp-svc", task.service, task)
    captured = {}

    def fake_launch(t, cluster_name=None, detach_run=None,
                    stream_logs=None):
        captured["num_nodes"] = t.num_nodes
        captured["envs"] = dict(t.envs)
        raise RuntimeError("stop before provisioning")

    monkeypatch.setattr(replica_managers.execution, "launch",
                        fake_launch)
    mgr.scale_up(1)
    for t in list(mgr._threads):
        t.join(timeout=30)
    assert captured["num_nodes"] == 2
    topo = json.loads(captured["envs"][gang_replica.TOPOLOGY_ENV])
    assert topo == {"hosts": 2, "ici_axes": {"tp": 2}}
    # One gang == one replica row.
    assert len(mgr.replicas) <= 1


# ===================================== mesh rules on the serving path
def _families():
    return [("llama", llama, llama.LlamaConfig.tiny(vocab_size=128)),
            ("mixtral", mixtral, mixtral.MixtralConfig.tiny()),
            ("gemma", gemma, gemma.GemmaConfig.tiny(vocab_size=128))]


def test_cache_specs_tp_sharding_all_families():
    """cache_specs resolves to a TP sharding on the kv_heads dim for
    every family whose head count divides the mesh — and re-points at
    the trailing head_dim axis (matching the packed kv projection's
    sharding, so donation survives) when it doesn't (gemma tiny's
    single KV head)."""
    mesh = mesh_lib.make_mesh({"tp": 2}, devices=jax.devices()[:2])
    rules = mesh_lib.DEFAULT_RULES
    for name, mdl, cfg in _families():
        specs = mdl.cache_specs(cfg)
        assert set(specs) == {"k", "v"}
        shardings = gang_replica.cache_shardings(cfg, mesh, rules)
        for key in ("k", "v"):
            spec = shardings[key].spec
            if cfg.n_kv_heads % 2 == 0:
                assert spec == mesh_lib.P(None, None, None, "tp"), \
                    (name, spec)
            else:
                assert spec == mesh_lib.P(None, None, None, None,
                                          "tp"), (name, spec)
        # The raw logical spec still names kv_heads for the divisible
        # case — the fallback is resolution-time, not spec-time.
        assert specs["k"][3] == "kv_heads"
        # Param side: the vocab projection and MLP shard over tp.
        psh = mesh_lib.tree_shardings(mesh, rules,
                                      mdl.param_specs(cfg))
        assert "tp" in str(psh["embed"].spec)


def test_sharded_engine_donation_preserved():
    """The KV cache stays donated through the SHARDED jitted decode and
    prefill entry points: the input buffers are deleted after each
    call, so the cache never silently doubles in HBM. Pinned per
    family on the serving path."""
    mesh = mesh_lib.make_mesh({"tp": 2}, devices=jax.devices()[:2])
    rules = mesh_lib.DEFAULT_RULES
    for name, mdl, cfg in _families():
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        params = gang_replica.shard_params(
            cfg, mdl.init(cfg, jax.random.key(0)), mesh, rules)
        cache = mdl.init_cache(cfg, 2, 128)
        shardings = gang_replica.cache_shardings(cfg, mesh, rules)
        # shardings also carries k_scale/v_scale for the int8 paged
        # pool; the dense cache has no such leaves — filter like the
        # engine does.
        cache = jax.device_put(cache,
                               {k: shardings[k] for k in cache})
        old_k, old_v = cache["k"], cache["v"]
        buf = jnp.zeros((64,), jnp.int32).at[:4].set(
            jnp.asarray([1, 2, 3, 4]))
        block = decode_engine._default_split_kv_block()
        _logits, cache = decode_engine._prefill_chunk(
            cfg, params, cache, buf, jnp.int32(0), jnp.int32(0),
            jnp.int32(4), block)
        assert old_k.is_deleted() and old_v.is_deleted(), \
            f"{name}: prefill chunk dropped the cache donation"
        old_k, old_v = cache["k"], cache["v"]
        _nxt, cache = decode_engine._engine_step(
            cfg, params, cache,
            jnp.zeros((2,), jnp.int32),
            jnp.asarray([4, 0], jnp.int32),
            jnp.zeros((2,), jnp.float32),
            jnp.zeros((2,), jnp.uint32), block)
        assert old_k.is_deleted() and old_v.is_deleted(), \
            f"{name}: decode step dropped the cache donation"


def test_tp_engine_bit_identical_to_single_process():
    """The tensor-parallel engine (params by param_specs, cache by
    cache_specs, tp=2 mesh) reproduces the single-process engine's
    token streams BIT-IDENTICALLY — greedy and seeded sampling — in
    f32 (bf16 matches only to bf16 rounding, like any resharding)."""
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=128),
                              dtype=jnp.float32)
    params = llama.init(cfg, jax.random.key(0))
    topo = gang_replica.ReplicaTopology(hosts=1, ici_axes={"tp": 2})
    mesh, rules = gang_replica.build_mesh(topo)
    sparams = gang_replica.shard_params(cfg, params, mesh, rules)

    reqs = [([1, 2, 3, 4, 5], 8, 0.0, 0),
            ([7, 9, 11], 10, 0.8, 123),
            ([4] * 70, 6, 0.0, 0),          # chunked prefill path
            ([5, 6], 8, 1.1, 7)]

    def run(engine):
        out = []
        try:
            handles = [engine.submit(p, max_tokens=mt,
                                     temperature=t, seed=s)
                       for p, mt, t, s in reqs]
            for h in handles:
                out.append(h.result(timeout=600.0))
        finally:
            engine.shutdown()
        return out

    ref = run(decode_engine.DecodeEngine(
        cfg, params, slots=2, max_seq=128).start())
    tp = run(decode_engine.DecodeEngine(
        cfg, sparams, slots=2, max_seq=128, mesh=mesh,
        rules=rules).start())
    assert tp == ref


# ==================================================== 2-process gang e2e
def _spawn_gang(port, env_extra=None, hosts=2, tp=2,
                model="tiny", dtype="float32"):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    env["STPU_GANG_HB_TIMEOUT"] = "2"
    env.update(env_extra or {})
    argv = [sys.executable, "-m", "skypilot_tpu.recipes.serve_llm",
            "--model", model, "--port", str(port),
            "--replica-hosts", str(hosts)]
    if tp > 1:
        argv += ["--tp", str(tp)]
    if dtype:
        argv += ["--dtype", dtype]
    return subprocess.Popen(argv, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)


def _wait_health(base, timeout=240, want=200):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if _get_code(base + "/health", timeout=5) == want:
            return True
        time.sleep(0.25)
    return False


def _terminate(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait(timeout=10)


@pytest.mark.usefixtures("tmp_state_dir")
def test_gang_replica_e2e_parity_failover_and_trace():
    """The acceptance story in one gang session: LB → host 0 → TP
    engine parity, follower kill mid-stream → 503 → whole-gang restart
    → LB recovers, all traced as one tree."""
    from skypilot_tpu.observability import tracing
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import (
        RoundRobinPolicy)

    # Single-process references, bit-for-bit: the engine's sampling
    # scheme (fold_in(root, seed), pos) is the contract, so the
    # reference is a plain in-process engine with identical cfg/seed.
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(),
                              dtype=jnp.float32)
    params = llama.init(cfg, jax.random.PRNGKey(0))
    ref_engine = decode_engine.DecodeEngine(
        cfg, params, slots=2, max_seq=128).start()
    try:
        greedy_ref = ref_engine.submit(
            [1, 2, 3, 4], max_tokens=8).result(timeout=600.0)
        sampled_ref = ref_engine.submit(
            [9, 8, 7], max_tokens=8, temperature=0.7,
            seed=42).result(timeout=600.0)
    finally:
        ref_engine.shutdown()

    tracing.arm()
    port = _free_port()
    proc = _spawn_gang(port, env_extra={"STPU_TRACE": "1"})
    lb_port = _free_port()
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([f"http://127.0.0.1:{port}"])
    lb = lb_lib.run_load_balancer(lb_port, policy,
                                  lb_lib.RequestRecorder())
    base = f"http://127.0.0.1:{lb_port}"
    try:
        assert _wait_health(base, timeout=240), \
            "gang replica never became healthy"

        # --- parity through LB → host 0 → TP engine
        _code, out = _post_json(base + "/generate",
                                {"prompt": [1, 2, 3, 4],
                                 "max_tokens": 8})
        assert out["tokens"] == greedy_ref
        _code, out = _post_json(base + "/generate",
                                {"prompt": [9, 8, 7], "max_tokens": 8,
                                 "temperature": 0.7, "seed": 42})
        assert out["tokens"] == sampled_ref

        # --- gang introspection: exactly one replica, two hosts
        gang = json.loads(_get(f"http://127.0.0.1:{port}/gang")[1])
        assert gang["label"] == "2x2"
        follower = [m for m in gang["members"]
                    if m["role"] == "follower"][0]

        # --- kill the follower MID-STREAM
        stream_req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_tokens": 64,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(stream_req, timeout=60)
        assert resp.read(16)            # stream is live
        os.kill(follower["pid"], signal.SIGKILL)
        deadline = time.monotonic() + 30
        flipped = False
        while time.monotonic() < deadline:
            if _get_code(f"http://127.0.0.1:{port}/health",
                         timeout=5) == 503:
                flipped = True
                break
            time.sleep(0.05)
        assert flipped, "/health never flipped to 503 on member death"
        try:
            resp.read()                 # stream ends or truncates
        except Exception:  # noqa: stpu-except — truncation IS the documented mid-stream failure signal
            pass
        resp.close()

        # --- whole-gang supervisor restart recovers the LB path
        assert _wait_health(base, timeout=120), \
            "gang never recovered after whole-gang restart"
        deadline = time.monotonic() + 60
        out = None
        while time.monotonic() < deadline:
            try:
                _code, out = _post_json(
                    base + "/generate",
                    {"prompt": [1, 2, 3, 4], "max_tokens": 8})
                break
            except (urllib.error.URLError, ConnectionError,
                    OSError):
                time.sleep(0.5)
        assert out is not None and out["tokens"] == greedy_ref, \
            "post-restart output diverged from the single-process " \
            "engine"
        gang = json.loads(_get(f"http://127.0.0.1:{port}/gang")[1])
        assert gang["restarts"] >= 1
        new_follower = [m for m in gang["members"]
                        if m["role"] == "follower"][0]
        assert new_follower["pid"] != follower["pid"]

        # --- one trace tree: lb.request → replica.generate → gang.run
        time.sleep(0.5)                 # let the sinks flush
        rows = [r for r in tracing.read()
                if r.get("name") == "lb.request"
                and r.get("attrs", {}).get("path") == "/generate"]
        assert rows, "no lb.request roots recorded"
        found = False
        for row in rows:
            for root in tracing.assemble(row["trace_id"]):
                gens = [c for c in root["children"]
                        if c["span"]["name"] == "replica.generate"]
                for gen in gens:
                    if any(g["span"]["name"] == "gang.run"
                           for g in gen["children"]):
                        found = True
        assert found, ("lb.request → replica.generate → gang.run "
                       "never assembled into one tree")
    finally:
        tracing.disarm()
        lb.shutdown()
        _terminate(proc)


# ======================================================== observability
def test_topology_info_gauge_in_replica_metrics():
    from skypilot_tpu.observability import metrics
    from skypilot_tpu.recipes import serve_llm

    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    ready = threading.Event()
    httpd = serve_llm.serve(
        cfg, params, 0, ready_event=ready, engine_slots=0,
        topology=gang_replica.ReplicaTopology(hosts=2,
                                              ici_axes={"tp": 4}))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        assert ready.wait(timeout=300)
        port = httpd.server_address[1]
        _status, body = _get(f"http://127.0.0.1:{port}/metrics")
        text = body.decode()
        assert ('stpu_replica_topology_info{hosts="2",tp="4"} 1'
                in text), text[-2000:]
    finally:
        httpd.shutdown()
    del metrics


def test_loadgen_report_carries_replica_topology(tmp_path):
    """The loadgen report attributes the run to the serving topology
    scraped from /metrics (stpu_replica_topology_info riding the LB
    merge), so an SLO regression next to a topology change reads as
    caused by it."""
    import http.server
    import socketserver

    from skypilot_tpu.benchmark import loadgen

    class _Metrics(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = (
                "# HELP stpu_replica_topology_info topo\n"
                "# TYPE stpu_replica_topology_info gauge\n"
                'stpu_replica_topology_info{hosts="2",tp="2"} 1\n'
            ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = socketserver.TCPServer(("127.0.0.1", 0), _Metrics)
    server.allow_reuse_address = True
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}"
        scraper = loadgen.MetricsScraper(
            url, interval=10.0, series_path=tmp_path / "m.jsonl")
        assert scraper.scrape_once() is not None
        sets = scraper.label_sets("stpu_replica_topology_info")
        assert sets == [{"hosts": "2", "tp": "2"}]
    finally:
        server.shutdown()
