"""Benchmark harness + step callbacks (reference analog:
sky/benchmark/benchmark_utils.py:73, sky/callbacks/sky_callback)."""
import json
import os
import sys
import time

import pytest
from click.testing import CliRunner

from skypilot_tpu import callbacks as sky_callback
from skypilot_tpu import cli as cli_mod
from skypilot_tpu.benchmark import benchmark_state, benchmark_utils
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_callbacks_noop_without_env(monkeypatch):
    monkeypatch.delenv(sky_callback.ENV_LOG_DIR, raising=False)
    assert sky_callback.init() is False
    # All calls are safe no-ops.
    sky_callback.step_begin()
    sky_callback.step_end()
    assert list(sky_callback.step_iterator([1, 2])) == [1, 2]


def test_callbacks_write_summary(tmp_path):
    assert sky_callback.init(total_steps=5, log_dir=str(tmp_path))
    for _ in sky_callback.step_iterator(range(5)):
        time.sleep(0.01)
    sky_callback.flush()
    summary = json.loads((tmp_path / sky_callback.SUMMARY_NAME
                          ).read_text())
    assert summary["num_steps"] == 5
    assert summary["total_steps"] == 5
    assert summary["seconds_per_step"] > 0


@pytest.mark.usefixtures("tmp_state_dir")
def test_benchmark_end_to_end_local():
    """Two local candidates run a tiny callback-armed workload; the
    harness collects summaries and derives sec/step."""
    script = (
        "import time; from skypilot_tpu import callbacks as cb; "
        "cb.init(total_steps=4); "
        "[(cb.step_begin(), time.sleep(0.05), cb.step_end()) "
        " for _ in range(4)]; cb.flush()")
    task = Task("bench-task",
                run=f"{sys.executable} -c {script!r}",
                envs={"PYTHONPATH": REPO_ROOT})
    task.set_resources(Resources(cloud="local"))

    names = benchmark_utils.launch_benchmark(
        task, [Resources(cloud="local"), Resources(cloud="local")],
        "b1")
    assert names == ["stpu-bench-b1-0", "stpu-bench-b1-1"]
    with pytest.raises(ValueError, match="already exists"):
        benchmark_utils.launch_benchmark(task, [], "b1")

    deadline = time.time() + 60
    rows = []
    while time.time() < deadline:
        rows = benchmark_utils.update_benchmark("b1")
        if all(r["status"] == "FINISHED" for r in rows):
            break
        time.sleep(0.5)
    assert len(rows) == 2
    for r in rows:
        assert r["status"] == "FINISHED", rows
        assert r["num_steps"] == 4
        assert r["seconds_per_step"] > 0
        assert "dollars_per_step" in r
        assert r["total_steps"] == 4
        assert "estimated_total_cost" in r

    benchmark_utils.teardown_benchmark("b1")
    from skypilot_tpu import global_user_state
    assert all(
        global_user_state.get_cluster_from_name(n) is None
        for n in names)
    # Results survive teardown.
    kept = benchmark_state.get_results("b1")
    assert all(r["status"] == "TERMINATED" and r["num_steps"] == 4
               for r in kept)

    runner = CliRunner()
    out = runner.invoke(cli_mod.cli, ["bench", "show", "b1"])
    assert out.exit_code == 0, out.output
    assert "stpu-bench-b1-0" in out.output
    out = runner.invoke(cli_mod.cli, ["bench", "delete", "b1"])
    assert out.exit_code == 0
    assert benchmark_state.get_results("b1") == []


@pytest.mark.usefixtures("tmp_state_dir")
def test_benchmark_fleet_launches_concurrently(monkeypatch):
    """VERDICT r3 weak #5: candidates provision in parallel — a serial
    sweep would deadlock this barrier."""
    import threading

    n = 3
    barrier = threading.Barrier(n, timeout=10)

    def fake_launch(task, cluster_name=None, detach_run=True,
                    stream_logs=False):
        barrier.wait()
        return 1, None

    monkeypatch.setattr(benchmark_utils.execution, "launch", fake_launch)
    names = benchmark_utils.launch_benchmark(
        Task("t", run="true"),
        [Resources(cloud="local") for _ in range(n)], "bpar")
    assert len(names) == n
    benchmark_state.delete_benchmark("bpar")


@pytest.mark.usefixtures("tmp_state_dir")
def test_benchmark_failed_candidate_rolls_back_fleet(monkeypatch):
    """One failing candidate tears the whole fleet down and releases
    the benchmark name for retry."""
    torn_down = []

    def fake_launch(task, cluster_name=None, detach_run=True,
                    stream_logs=False):
        if cluster_name.endswith("-1"):
            raise RuntimeError("zone out of capacity")
        return 1, None

    def fake_teardown(benchmark, terminate=True):
        torn_down.append(benchmark)

    monkeypatch.setattr(benchmark_utils.execution, "launch", fake_launch)
    monkeypatch.setattr(benchmark_utils, "teardown_benchmark",
                        fake_teardown)
    with pytest.raises(RuntimeError, match="capacity"):
        benchmark_utils.launch_benchmark(
            Task("t", run="true"),
            [Resources(cloud="local") for _ in range(3)], "broll")
    assert torn_down == ["broll"]
    # Name released: relaunch is possible.
    assert all(b["name"] != "broll"
               for b in benchmark_state.get_benchmarks())


def test_flax_wrap_train_step_records(tmp_path, monkeypatch):
    """The jax/flax integration times each step call and writes the
    summary (reference integrations analog: sky_callback/integrations;
    VERDICT r4 missing #3)."""
    from skypilot_tpu import callbacks
    from skypilot_tpu.integrations.flax import wrap_train_step
    monkeypatch.setenv(callbacks.ENV_LOG_DIR, str(tmp_path))
    monkeypatch.setattr(callbacks, "_state", None)  # isolate recorder

    calls = []

    def step(state, batch):
        calls.append(batch)
        return state

    wrapped = wrap_train_step(step, total_steps=5)
    s = 0
    for i in range(5):
        s = wrapped(s, i)
    callbacks.flush()
    summary = json.loads((tmp_path / "benchmark_summary.json").read_text())
    assert summary["num_steps"] == 5
    assert summary["total_steps"] == 5
    assert calls == [0, 1, 2, 3, 4]


def test_transformers_callback_records(tmp_path, monkeypatch):
    """The HF Trainer callback drives the same recorder through the
    TrainerCallback event surface (hooks invoked directly — a real
    Trainer run needs a model; the event contract is what's ours)."""
    from skypilot_tpu import callbacks
    import pytest as _pytest
    _pytest.importorskip("transformers")  # baked into this image, but
    # not a declared dependency — a clean install must skip, not error.
    from skypilot_tpu.integrations.transformers import (
        SkyTransformersCallback)
    from transformers import TrainerCallback
    monkeypatch.setenv(callbacks.ENV_LOG_DIR, str(tmp_path))

    monkeypatch.setattr(callbacks, "_state", None)  # isolate recorder
    cb = SkyTransformersCallback()
    assert isinstance(cb, TrainerCallback)  # real HF surface

    class _State:
        max_steps = 3

    cb.on_train_begin(None, _State(), None)
    for _ in range(3):
        cb.on_step_begin(None, _State(), None)
        cb.on_step_end(None, _State(), None)
    cb.on_train_end(None, _State(), None)
    summary = json.loads((tmp_path / "benchmark_summary.json").read_text())
    assert summary["num_steps"] == 3
    assert summary["total_steps"] == 3


def test_integrations_noop_without_env(monkeypatch, tmp_path):
    from skypilot_tpu import callbacks
    from skypilot_tpu.integrations.flax import wrap_train_step
    monkeypatch.delenv(callbacks.ENV_LOG_DIR, raising=False)
    monkeypatch.setattr(callbacks, "_state", None)  # isolate recorder
    wrapped = wrap_train_step(lambda s, b: s)
    for i in range(3):
        wrapped(0, i)
    # The real contract: no recorder armed, nothing written anywhere.
    assert callbacks._state is None
    assert not list(tmp_path.iterdir())
