"""GPipe pipeline parallelism tests (8-device CPU mesh)."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib, pipeline
from skypilot_tpu.train import trainer


def test_gpipe_matches_sequential_stages():
    """A stack of affine stages pipelined == applied sequentially."""
    mesh = mesh_lib.make_mesh({"pp": 4, "tp": 2})
    n_stages, m, mb, d = 4, 4, 2, 16
    w = jax.random.normal(jax.random.key(0), (n_stages, d, d)) * 0.3
    x = jax.random.normal(jax.random.key(1), (m, mb, d))

    def stage_fn(lp, x_mb, _ex):
        return jnp.tanh(x_mb @ lp["w"])

    out = jax.jit(lambda w, x: pipeline.gpipe(
        stage_fn, {"w": w}, x, mesh=mesh, num_microbatches=m))(w, x)

    ref = x
    for i in range(n_stages):
        ref = jnp.tanh(ref @ w[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gpipe_no_pp_axis_sequential_fallback():
    mesh = mesh_lib.make_mesh({"dp": 8})
    n_stages, m, mb, d = 3, 2, 4, 8
    w = jax.random.normal(jax.random.key(0), (n_stages, d, d)) * 0.3
    x = jax.random.normal(jax.random.key(1), (m, mb, d))

    def stage_fn(lp, x_mb, _ex):
        return jnp.tanh(x_mb @ lp["w"])

    out = pipeline.gpipe(stage_fn, {"w": w}, x, mesh=mesh,
                         num_microbatches=m)
    ref = x
    for i in range(n_stages):
        ref = jnp.tanh(ref @ w[i])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_llama_pipelined_matches_plain_forward():
    # f32 so pipelined vs plain is exact up to reassociation, not bf16 noise
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=64),
                              dtype=jnp.float32)
    mesh = mesh_lib.make_mesh({"dp": 2, "pp": 2, "tp": 2})
    rules = mesh_lib.PIPELINE_RULES
    params = llama.init(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, 64)

    plain = llama.forward(cfg, params, tokens)
    piped = jax.jit(lambda p, t: llama.forward_pipelined(
        cfg, p, t, mesh=mesh, rules=rules, num_microbatches=2))(
            params, tokens)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                               rtol=1e-4, atol=1e-4)


def test_llama_pipelined_trains():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    mesh = mesh_lib.make_mesh({"dp": 2, "pp": 2, "tp": 2})
    rules = mesh_lib.PIPELINE_RULES
    params = llama.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(
        learning_rate=1e-2, warmup_steps=1, total_steps=30))
    state = trainer.init_train_state(params, tx)
    step = trainer.make_train_step(
        lambda p, t, constrain: llama.forward_pipelined(
            cfg, p, t, mesh=mesh, rules=rules, num_microbatches=2),
        tx, mesh, rules)
    tokens = jax.random.randint(jax.random.key(2), (4, 32), 0, 64)
    state, m0 = step(state, {"tokens": tokens})
    for _ in range(8):
        state, m = step(state, {"tokens": tokens})
    assert float(m["loss"]) < float(m0["loss"])
