"""Crash-consistent checkpointing: atomic-rename durability, torn-file
fallback, retention GC, the async Checkpointer, SIGKILL-mid-save chaos,
kill-and-resume bit-parity for two model families, and the tier-1
atomic-writes lint.

The acceptance bar (ISSUE 6): a killed host/process costs < one
--ckpt-every interval of recomputed work, and a resumed run is
BIT-identical to an uninterrupted one.
"""
import json
import os
import pathlib
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from skypilot_tpu.train import checkpoint as ck
from skypilot_tpu.utils import fault_injection as fi

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


def _tree(scale=1.0):
    import jax.numpy as jnp
    import optax
    lora = {"layers": {"wq_a": jnp.full((4, 3), scale, jnp.bfloat16),
                       "wq_b": jnp.arange(6, dtype=jnp.float32)
                       .reshape(3, 2) * scale}}
    opt_state = optax.adamw(1e-3).init(lora)
    return {"lora": lora, "opt_state": opt_state,
            "step": np.int64(0), "data_pos": np.int64(0),
            "rng": np.array([7, 9], dtype=np.uint32)}


# ------------------------------------------------------------ round trip
def test_roundtrip_bit_identical(tmp_path):
    """Raw-byte round trip: bfloat16 params, optax NamedTuple optimizer
    state, scalars — restored values AND pytree structure match."""
    tree = _tree()
    ck.save(tmp_path, 7, tree, meta={"note": "hello"})
    restored = ck.restore_latest(tmp_path, like=tree)
    assert restored is not None and restored.step == 7
    assert restored.meta["note"] == "hello"
    got = restored.tree
    assert got["lora"]["layers"]["wq_a"].dtype.name == "bfloat16"
    np.testing.assert_array_equal(
        np.asarray(got["lora"]["layers"]["wq_a"]).view(np.uint16),
        np.asarray(tree["lora"]["layers"]["wq_a"]).view(np.uint16))
    # Optimizer-state structure survives (NamedTuple types, not bare
    # tuples — a treedef mismatch would silently retrace jitted steps).
    def _types(t):
        if isinstance(t, tuple):
            return (type(t).__name__,) + tuple(_types(c) for c in t)
        return type(t).__name__
    assert _types(got["opt_state"])[0] == _types(tree["opt_state"])[0]
    # Identical states produce byte-identical payloads (parity handle).
    ck.save(tmp_path, 8, tree)
    man7 = json.loads((tmp_path / "ckpt-00000007.json").read_text())
    man8 = json.loads((tmp_path / "ckpt-00000008.json").read_text())
    assert man7["sha256"] == man8["sha256"]


def test_restore_skips_torn_and_corrupt(tmp_path):
    tree = _tree()
    ck.save(tmp_path, 1, tree)
    ck.save(tmp_path, 2, _tree(scale=2.0))
    ck.save(tmp_path, 3, _tree(scale=3.0))
    # Step 3: torn payload (truncated write).
    p3 = tmp_path / "ckpt-00000003.bin"
    p3.write_bytes(p3.read_bytes()[:-5])
    # Step 2: silent bit corruption (size intact, checksum mismatch).
    p2 = tmp_path / "ckpt-00000002.bin"
    raw = bytearray(p2.read_bytes())
    raw[0] ^= 0xFF
    p2.write_bytes(bytes(raw))
    before = ck._SKIPPED.labels().get()
    restored = ck.restore_latest(tmp_path, like=tree)
    assert restored is not None and restored.step == 1
    assert ck._SKIPPED.labels().get() - before == 2


def test_restore_skips_unreadable_manifest(tmp_path):
    tree = _tree()
    ck.save(tmp_path, 1, tree)
    (tmp_path / "ckpt-00000002.json").write_text("{not json")
    restored = ck.restore_latest(tmp_path)
    assert restored is not None and restored.step == 1


def test_restore_none_when_empty(tmp_path):
    assert ck.restore_latest(tmp_path) is None
    assert ck.latest_step(tmp_path) is None


def test_retention_gc(tmp_path):
    tree = _tree()
    for step in range(1, 6):
        ck.save(tmp_path, step, tree, keep=2)
    assert ck.steps(tmp_path) == [4, 5]
    # Payloads of GC'd steps are gone too.
    assert not (tmp_path / "ckpt-00000001.bin").exists()


def test_structure_mismatch_fails_loudly(tmp_path):
    ck.save(tmp_path, 1, {"a": np.ones(3)})
    with pytest.raises(ck.CheckpointError, match="missing leaf"):
        ck.restore_latest(tmp_path, like={"a": np.ones(3),
                                          "b": np.ones(2)})


def test_none_leaves_roundtrip(tmp_path):
    tree = {"x": np.ones(2), "sched": None}
    ck.save(tmp_path, 1, tree)
    restored = ck.restore_latest(tmp_path, like=tree)
    assert restored.tree["sched"] is None


# ------------------------------------------------------------- async saver
def test_checkpointer_async_orders_saves(tmp_path):
    saver = ck.Checkpointer(tmp_path, keep=2)
    for step in (1, 2, 3):
        saver.save(step, {"w": np.full(4, step)})
    saver.wait()
    assert saver.last_saved_step == 3
    assert ck.latest_step(tmp_path) == 3
    restored = ck.restore_latest(tmp_path)
    np.testing.assert_array_equal(restored.tree["w"], np.full(4, 3))


def test_checkpointer_surfaces_background_errors(tmp_path):
    # A regular file where the ckpt dir should be: mkdir fails in the
    # background writer. (chmod tricks don't work — tests run as root.)
    (tmp_path / "blocker").write_text("not a directory")
    saver = ck.Checkpointer(tmp_path / "blocker" / "ckpts")
    saver.save(1, {"w": np.ones(2)})
    with pytest.raises(ck.CheckpointError,
                       match="background checkpoint save failed"):
        saver.wait()


# ------------------------------------------------------------------ chaos
def test_sigkill_mid_save_leaves_latest_valid(tmp_path):
    """Acceptance: SIGKILL during a checkpoint write leaves a
    restorable latest-valid checkpoint — the torn temp file is never
    even considered by restore."""
    script = textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {REPO_ROOT!r})
        import numpy as np
        from skypilot_tpu.train import checkpoint as ck
        from skypilot_tpu.utils import fault_injection as fi
        d = {str(tmp_path)!r}
        ck.save(d, 1, {{"w": np.arange(8)}})
        fi.activate("ckpt.write", mode="kill")
        ck.save(d, 2, {{"w": np.arange(8) * 2}})
        raise SystemExit("unreachable: kill fired")
    """)
    proc = subprocess.run([sys.executable, "-c", script],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == -signal.SIGKILL, proc.stderr
    # The kill fired after the payload bytes, before the rename: step 2
    # left only a temp file.
    names = sorted(os.listdir(tmp_path))
    assert any(".tmp-" in n for n in names), names
    assert not (tmp_path / "ckpt-00000002.json").exists()
    restored = ck.restore_latest(tmp_path)
    assert restored is not None and restored.step == 1
    np.testing.assert_array_equal(restored.tree["w"], np.arange(8))


def test_fault_kill_mode_and_skip_param_parse():
    rules = fi.parse_spec("train.step:kill:skip=4,times=1")
    assert rules[0].mode == "kill"
    assert rules[0].skip == 4 and rules[0].times == 1
    with pytest.raises(fi.FaultSpecError):
        fi.parse_spec("x:explode")


def test_fault_skip_defers_firing():
    with fi.inject("t.skip", times=1, skip=2):
        fi.fire("t.skip")          # eligible hit 1: skipped
        fi.fire("t.skip")          # eligible hit 2: skipped
        with pytest.raises(fi.InjectedFault):
            fi.fire("t.skip")      # hit 3: fires
        fi.fire("t.skip")          # times budget exhausted
        assert fi.fires("t.skip") == 1


# --------------------------------------------- kill-and-resume parity
def _run_recipe(module, ckpt_dir, steps, extra_env=None, argv=()):
    env = dict(os.environ)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-m", module, "--steps", str(steps),
         "--batch-size", "2", "--seq-len", "64",
         "--checkpoint-dir", str(ckpt_dir), "--ckpt-every", "2",
         "--ckpt-sync", *argv],
        capture_output=True, text=True, env=env, timeout=300)


def _final_payload_sha(ckpt_dir):
    manifests = sorted(pathlib.Path(ckpt_dir).glob("ckpt-*.json"))
    return json.loads(manifests[-1].read_text())["sha256"]


@pytest.mark.parametrize("module", [
    "skypilot_tpu.recipes.llama_lora",
    "skypilot_tpu.recipes.gemma_lora",
])
def test_kill_and_resume_parity(module, tmp_path):
    """Acceptance: train N steps uninterrupted vs train + SIGKILL
    mid-run + resume — final params/opt-state/loss BIT-identical, and
    the resumed run replays < ckpt_every steps."""
    steps, ckpt_every, kill_at = 6, 2, 5
    plain_dir = tmp_path / "plain"
    chaos_dir = tmp_path / "chaos"

    plain = _run_recipe(module, plain_dir, steps)
    assert plain.returncode == 0, plain.stderr[-2000:]
    plain_metrics = json.loads(plain.stdout.strip().splitlines()[-1])

    # SIGKILL (via the train.step seam in kill mode) right after step 5
    # completes — the newest durable checkpoint is step 4.
    killed = _run_recipe(
        module, chaos_dir, steps,
        extra_env={"STPU_FAULTS":
                   f"train.step:kill:skip={kill_at - 1},times=1"})
    assert killed.returncode == -signal.SIGKILL, killed.stderr[-2000:]
    from skypilot_tpu.train import checkpoint as ck_lib
    assert ck_lib.latest_step(chaos_dir) == kill_at - 1

    resumed = _run_recipe(module, chaos_dir, steps)
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    resumed_metrics = json.loads(
        resumed.stdout.strip().splitlines()[-1])
    # Replays exactly kill_at - latest_ckpt = 1 step (< ckpt_every).
    assert resumed_metrics["resumed_from"] == kill_at - 1
    assert kill_at - resumed_metrics["resumed_from"] < ckpt_every
    # Bit-identical: the final checkpoint payload (adapters + optimizer
    # state + step + data position + PRNG key, raw bytes) and the loss.
    assert resumed_metrics["final_loss"] == plain_metrics["final_loss"]
    assert _final_payload_sha(plain_dir) == _final_payload_sha(chaos_dir)


# ---------------------------------------------------- SIGTERM grace
def test_sigterm_grace_saves_and_exits_143(tmp_path):
    """Preemption grace: SIGTERM mid-run → the loop finishes the step,
    saves a final checkpoint, exits rc 143 (not 0: the controller must
    still treat the task as interrupted)."""
    env = dict(os.environ)
    # Slow each step down via the delay fault so the signal reliably
    # lands mid-run, not after the last step.
    env["STPU_FAULTS"] = "train.step:delay:s=0.3"
    proc = subprocess.Popen(
        [sys.executable, "-m", "skypilot_tpu.recipes.llama_lora",
         "--steps", "500", "--batch-size", "2", "--seq-len", "64",
         "--checkpoint-dir", str(tmp_path), "--ckpt-every", "1",
         "--ckpt-sync"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    # The first checkpoint (--ckpt-every 1) proves the loop — and the
    # grace handler installed just before it — is live; only then is
    # SIGTERM guaranteed the 143 path rather than the default handler.
    import time
    deadline = time.time() + 240
    while time.time() < deadline and ck.latest_step(tmp_path) is None:
        assert proc.poll() is None, proc.communicate()[0][-2000:]
        time.sleep(0.2)
    assert ck.latest_step(tmp_path) is not None, "loop never started"
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == ck.GraceHandler.GRACE_EXIT_CODE, out[-2000:]
    last = json.loads(out.strip().splitlines()[-1])
    assert last["preempted"] is True
    # The grace save is durable and restorable at the stopped step.
    assert ck.latest_step(tmp_path) == last["stopped_at"]
    assert ck.restore_latest(tmp_path) is not None


# ------------------------------------------------------ observability
def test_ckpt_metrics_families_exposed(tmp_path):
    """The ckpt metric families ride the shared registry exposition
    (scraped by replica /metrics and dumped by controllers)."""
    from skypilot_tpu.observability import metrics as metrics_lib
    ck.save(tmp_path, 3, {"w": np.ones(4)})
    ck.restore_latest(tmp_path)
    text = metrics_lib.render()
    for family in ("stpu_ckpt_save_seconds", "stpu_ckpt_restore_seconds",
                   "stpu_ckpt_saves_total", "stpu_ckpt_last_step"):
        assert family in text, family


def test_restore_falls_back_on_unresolvable_dtype(tmp_path):
    """A manifest naming a dtype this environment can't resolve (newer
    writer / corrupt manifest) costs one checkpoint, never the run."""
    ck.save(tmp_path, 1, {"w": np.arange(3)})
    ck.save(tmp_path, 2, {"w": np.arange(3) * 2})
    man = tmp_path / "ckpt-00000002.json"
    doc = json.loads(man.read_text())
    doc["leaves"][0]["dtype"] = "float8_from_the_future"
    man.write_text(json.dumps(doc))
    restored = ck.restore_latest(tmp_path)
    assert restored is not None and restored.step == 1


def test_async_and_sync_payloads_byte_identical(tmp_path):
    """The parity handle rests on this: the async Checkpointer and a
    sync save() of the same tree produce byte-identical payloads, even
    with sequence nodes of >= 10 children (lexical-vs-positional key
    ordering trap)."""
    tree = {"chain": tuple(np.full(3, i) for i in range(12)),
            "step": np.int64(4)}
    sync_dir, async_dir = tmp_path / "s", tmp_path / "a"
    ck.save(sync_dir, 1, tree)
    saver = ck.Checkpointer(async_dir)
    saver.save(1, tree)
    saver.wait()
    sha = lambda d: json.loads(
        (d / "ckpt-00000001.json").read_text())["sha256"]
    assert sha(sync_dir) == sha(async_dir)
    restored = ck.restore_latest(async_dir, like=tree)
    np.testing.assert_array_equal(restored.tree["chain"][10],
                                  np.full(3, 10))
