"""CLI surface tests via click's runner (reference: tests/test_cli.py)."""
import pytest
from click.testing import CliRunner

from skypilot_tpu import cli


@pytest.fixture
def runner():
    return CliRunner()


def test_help_lists_commands(runner):
    result = runner.invoke(cli.cli, ["--help"])
    assert result.exit_code == 0
    for cmd in ("launch", "exec", "status", "stop", "down", "autostop",
                "queue", "logs", "cancel", "check", "show-tpus",
                "cost-report"):
        assert cmd in result.output


def test_show_tpus_filter(runner):
    result = runner.invoke(cli.cli, ["show-tpus", "v5p-64"])
    assert result.exit_code == 0
    assert "tpu-v5p-64" in result.output
    assert "us-east5-a" in result.output


def test_launch_dryrun(runner, tmp_state_dir, tmp_path):
    yaml_path = tmp_path / "t.yaml"
    yaml_path.write_text(
        "resources:\n  accelerators: tpu-v5e-8\nrun: echo hi\n")
    result = runner.invoke(
        cli.cli, ["launch", str(yaml_path), "--dryrun", "-c", "dry"])
    assert result.exit_code == 0, result.output
    assert "would provision" in result.output


def test_launch_local_end_to_end(runner, tmp_state_dir, capfd):
    result = runner.invoke(cli.cli, [
        "launch", "examples/local_smoke.yaml", "-c", "smoke",
        "--detach-run"])
    assert result.exit_code == 0, result.output
    assert "Job submitted: 1" in result.output

    result = runner.invoke(cli.cli, ["status"])
    assert "smoke" in result.output

    result = runner.invoke(cli.cli, ["queue", "smoke", "-a"])
    assert result.exit_code == 0, result.output

    # Wait for the job then read its logs.
    import time
    from skypilot_tpu import core
    deadline = time.time() + 20
    while time.time() < deadline:
        jobs = core.queue("smoke")
        if jobs and jobs[0]["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.2)
    capfd.readouterr()  # drain
    result = runner.invoke(cli.cli, ["logs", "smoke", "1", "--no-follow"])
    # Log lines stream from the head-side job_cli SUBPROCESS, so they
    # land on the real fd, not click's captured sys.stdout.
    assert "host rank 0 / 4" in capfd.readouterr().out

    result = runner.invoke(cli.cli, ["down", "smoke", "-y"])
    assert result.exit_code == 0, result.output
    result = runner.invoke(cli.cli, ["status"])
    assert "No existing clusters" in result.output


def test_env_override_required(runner, tmp_state_dir, tmp_path):
    yaml_path = tmp_path / "t.yaml"
    yaml_path.write_text(
        "envs:\n  TOKEN:\nrun: echo $TOKEN\n"
        "resources:\n  cloud: local\n")
    result = runner.invoke(cli.cli, ["launch", str(yaml_path), "--dryrun"])
    assert result.exit_code != 0
    assert "TOKEN" in result.output


def test_logs_sync_down(runner, tmp_state_dir):
    """`stpu logs --sync-down` pulls the head's job log files to the
    client (reference: sync_down_logs, cloud_vm_ray_backend.py:3540)."""
    import pathlib
    import time

    from skypilot_tpu import core
    result = runner.invoke(cli.cli, [
        "launch", "examples/local_smoke.yaml", "-c", "dl",
        "--detach-run"])
    assert result.exit_code == 0, result.output
    deadline = time.time() + 30
    while time.time() < deadline:
        jobs = core.queue("dl")
        if jobs and jobs[0]["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.2)
    got = core.download_logs("dl")
    assert got, "no logs downloaded"
    path = pathlib.Path(got[jobs[0]["job_id"]])
    logs = list(path.glob("node-*.log"))
    assert logs, f"no node logs under {path}"
    assert "host rank 0" in (path / "node-0.log").read_text()
    runner.invoke(cli.cli, ["down", "dl", "-y"])
