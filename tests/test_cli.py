"""CLI surface tests via click's runner (reference: tests/test_cli.py)."""
import pytest
from click.testing import CliRunner

from skypilot_tpu import cli


@pytest.fixture
def runner():
    return CliRunner()


def test_help_lists_commands(runner):
    result = runner.invoke(cli.cli, ["--help"])
    assert result.exit_code == 0
    for cmd in ("launch", "exec", "status", "stop", "down", "autostop",
                "queue", "logs", "cancel", "check", "show-tpus",
                "cost-report"):
        assert cmd in result.output


def test_show_tpus_filter(runner):
    result = runner.invoke(cli.cli, ["show-tpus", "v5p-64"])
    assert result.exit_code == 0
    assert "tpu-v5p-64" in result.output
    assert "us-east5-a" in result.output


def test_launch_dryrun(runner, tmp_state_dir, tmp_path):
    yaml_path = tmp_path / "t.yaml"
    yaml_path.write_text(
        "resources:\n  accelerators: tpu-v5e-8\nrun: echo hi\n")
    result = runner.invoke(
        cli.cli, ["launch", str(yaml_path), "--dryrun", "-c", "dry"])
    assert result.exit_code == 0, result.output
    assert "would provision" in result.output


def test_launch_local_end_to_end(runner, tmp_state_dir, capfd):
    result = runner.invoke(cli.cli, [
        "launch", "examples/local_smoke.yaml", "-c", "smoke",
        "--detach-run", "-y"])
    assert result.exit_code == 0, result.output
    assert "Job submitted: 1" in result.output

    result = runner.invoke(cli.cli, ["status"])
    assert "smoke" in result.output

    result = runner.invoke(cli.cli, ["queue", "smoke", "-a"])
    assert result.exit_code == 0, result.output

    # Wait for the job then read its logs.
    import time
    from skypilot_tpu import core
    deadline = time.time() + 20
    while time.time() < deadline:
        jobs = core.queue("smoke")
        if jobs and jobs[0]["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.2)
    capfd.readouterr()  # drain
    result = runner.invoke(cli.cli, ["logs", "smoke", "1", "--no-follow"])
    # Log lines stream from the head-side job_cli SUBPROCESS, so they
    # land on the real fd, not click's captured sys.stdout.
    assert "host rank 0 / 4" in capfd.readouterr().out

    result = runner.invoke(cli.cli, ["down", "smoke", "-y"])
    assert result.exit_code == 0, result.output
    result = runner.invoke(cli.cli, ["status"])
    assert "No existing clusters" in result.output


def test_env_override_required(runner, tmp_state_dir, tmp_path):
    yaml_path = tmp_path / "t.yaml"
    yaml_path.write_text(
        "envs:\n  TOKEN:\nrun: echo $TOKEN\n"
        "resources:\n  cloud: local\n")
    result = runner.invoke(cli.cli, ["launch", str(yaml_path), "--dryrun"])
    assert result.exit_code != 0
    assert "TOKEN" in result.output


def test_logs_sync_down(runner, tmp_state_dir):
    """`stpu logs --sync-down` pulls the head's job log files to the
    client (reference: sync_down_logs, cloud_vm_ray_backend.py:3540)."""
    import pathlib
    import time

    from skypilot_tpu import core
    result = runner.invoke(cli.cli, [
        "launch", "examples/local_smoke.yaml", "-c", "dl", "-y",
        "--detach-run"])
    assert result.exit_code == 0, result.output
    deadline = time.time() + 30
    while time.time() < deadline:
        jobs = core.queue("dl")
        if jobs and jobs[0]["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.2)
    got = core.download_logs("dl")
    assert got, "no logs downloaded"
    path = pathlib.Path(got[jobs[0]["job_id"]])
    logs = list(path.glob("node-*.log"))
    assert logs, f"no node logs under {path}"
    assert "host rank 0" in (path / "node-0.log").read_text()
    runner.invoke(cli.cli, ["down", "dl", "-y"])


def test_launch_confirmation_prompt(runner, tmp_state_dir, tmp_path):
    """Launching a NEW cluster prints the plan and asks (reference:
    sky/cli.py:562-592); 'n' aborts without provisioning; -y and
    --dryrun skip the prompt (VERDICT r4 next #5)."""
    yaml_path = tmp_path / "t.yaml"
    yaml_path.write_text("resources:\n  cloud: local\nrun: echo hi\n")

    result = runner.invoke(
        cli.cli, ["launch", str(yaml_path), "-c", "conf"], input="n\n")
    assert result.exit_code != 0
    assert "Launching a new cluster 'conf'. Proceed?" in result.output
    assert "Optimized plan" in result.output
    from skypilot_tpu import global_user_state
    assert global_user_state.get_cluster_from_name("conf") is None

    # --dryrun: no prompt at all.
    result = runner.invoke(
        cli.cli, ["launch", str(yaml_path), "--dryrun", "-c", "conf"])
    assert result.exit_code == 0, result.output
    assert "Proceed?" not in result.output

    # 'y' answer proceeds end-to-end; the second launch onto the now-UP
    # cluster skips the prompt (reuse is not a new spend).
    result = runner.invoke(
        cli.cli, ["launch", str(yaml_path), "-c", "conf",
                  "--detach-run"], input="y\n")
    assert result.exit_code == 0, result.output
    result = runner.invoke(
        cli.cli, ["launch", str(yaml_path), "-c", "conf",
                  "--detach-run"])
    assert result.exit_code == 0, result.output
    assert "existing cluster" in result.output
    assert "Proceed?" not in result.output
    runner.invoke(cli.cli, ["down", "conf", "--yes"])


def test_jobs_launch_confirmation(runner, tmp_state_dir, tmp_path):
    yaml_path = tmp_path / "j.yaml"
    yaml_path.write_text(
        "name: cj\nresources:\n  cloud: local\nrun: echo hi\n")
    result = runner.invoke(
        cli.cli, ["jobs", "launch", str(yaml_path)], input="n\n")
    assert result.exit_code != 0
    assert "Launching managed job" in result.output
    from skypilot_tpu.jobs import core as jobs_core
    assert jobs_core.queue() == []


def test_status_and_queue_table_columns(runner, tmp_state_dir):
    """Status/queue tables carry the reference's columns: launch age,
    head IP, $/hr; submitted/started/duration (VERDICT r4 next #7)."""
    result = runner.invoke(cli.cli, [
        "launch", "examples/local_smoke.yaml", "-c", "tbl",
        "--detach-run", "-y"])
    assert result.exit_code == 0, result.output

    result = runner.invoke(cli.cli, ["status"])
    assert result.exit_code == 0, result.output
    header, *rows = [l for l in result.output.splitlines() if l.strip()]
    for col in ("NAME", "LAUNCHED", "RESOURCES", "NODES", "STATUS",
                "AUTOSTOP", "HEAD_IP", "$/HR"):
        assert col in header, header
    row = next(l for l in rows if l.startswith("tbl"))
    assert "ago" in row           # human launch age
    assert "0.00" in row          # $/hr (local provider: free)

    # Job finishes -> queue shows submitted/started/duration.
    import time
    from skypilot_tpu import core
    deadline = time.time() + 20
    while time.time() < deadline:
        jobs = core.queue("tbl")
        if jobs and jobs[0]["status"] in ("SUCCEEDED", "FAILED"):
            break
        time.sleep(0.2)
    result = runner.invoke(cli.cli, ["queue", "tbl", "-a"])
    assert result.exit_code == 0, result.output
    header = next(l for l in result.output.splitlines() if "ID" in l)
    for col in ("SUBMITTED", "STARTED", "DURATION", "STATUS"):
        assert col in header, header
    assert "ago" in result.output
    runner.invoke(cli.cli, ["down", "tbl", "--yes"])


def test_down_accepts_glob_patterns(runner, tmp_state_dir, tmp_path):
    """`stpu down "pat-*"` expands against recorded clusters
    (reference: _get_glob_clusters)."""
    yaml_path = tmp_path / "t.yaml"
    yaml_path.write_text("resources:\n  cloud: local\nrun: echo hi\n")
    for name in ("gl-a", "gl-b", "other"):
        result = runner.invoke(cli.cli, [
            "launch", str(yaml_path), "-c", name, "--detach-run", "-y"])
        assert result.exit_code == 0, result.output
    result = runner.invoke(cli.cli, ["down", "gl-*", "--yes"])
    assert result.exit_code == 0, result.output
    assert "Terminated gl-a." in result.output
    assert "Terminated gl-b." in result.output
    assert "other" not in result.output
    result = runner.invoke(cli.cli, ["status"])
    assert "other" in result.output and "gl-a" not in result.output
    result = runner.invoke(cli.cli, ["down", "nope-*", "--yes"])
    assert "No clusters match" in result.output
    # A typo literal mixed with a glob reports the error AFTER the
    # matched clusters were still torn down.
    result = runner.invoke(cli.cli, ["down", "typo-name", "other",
                                     "--yes"])
    assert result.exit_code != 0
    assert "Terminated other." in result.output
    result = runner.invoke(cli.cli, ["status"])
    assert "other" not in result.output
