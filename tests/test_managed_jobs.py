"""Managed jobs: launch, preemption recovery, user failure, cancel,
pipelines — all hermetic on the local provider.

Reference test analog: tests/test_jobs.py + the recovery paths that the
reference can only exercise in real-cloud smoke tests; our local provider's
simulate_preemption makes them unit-testable (SURVEY §4 takeaway).
"""
import os
import time

import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import jobs
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.provision import local as local_provider
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


@pytest.fixture(autouse=True)
def fast_poll(monkeypatch):
    monkeypatch.setenv("STPU_JOBS_POLL_SECONDS", "0.2")


def _local_res(**kw):
    return Resources(cloud="local", **kw)


def _wait_status(job_id, statuses, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = jobs_state.get_status(job_id)
        if st in statuses:
            return st
        time.sleep(0.1)
    raise TimeoutError(f"job {job_id} stuck at {st}, wanted {statuses}")


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_success_inline():
    task = Task("mj-ok", run="echo managed-ok")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=False)
    assert jobs_state.get_status(job_id) == ManagedJobStatus.SUCCEEDED
    job = jobs_state.get_job(job_id)
    assert job["recovery_count"] == 0
    # Task cluster must not outlive the job.
    from skypilot_tpu import global_user_state
    assert global_user_state.get_cluster_from_name(
        job["cluster_name"]) is None


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_user_failure_not_recovered():
    task = Task("mj-fail", run="exit 7")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=False)
    job = jobs_state.get_job(job_id)
    assert job["status"] == "FAILED"
    assert job["recovery_count"] == 0


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_preemption_recovery(tmp_path):
    """Preempt the cluster mid-run; the controller must relaunch and the
    second attempt succeeds (EAGER_NEXT_REGION default strategy)."""
    marker = tmp_path / "attempts"
    task = Task("mj-recover", run=(
        f'n=$(cat {marker} 2>/dev/null || echo 0); '
        f'echo $((n+1)) > {marker}; '
        f'if [ "$n" -ge 1 ]; then echo recovered-ok; else sleep 120; fi'))
    task.set_resources(_local_res(use_spot=True))
    job_id = jobs.launch(task, detach=True, controller="local")

    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    # Wait for attempt 1 to actually start (marker written).
    deadline = time.time() + 30
    while not marker.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert marker.exists()

    cluster_name = jobs_state.get_job(job_id)["cluster_name"]
    local_provider.simulate_preemption(cluster_name)

    status = _wait_status(
        job_id, {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                 ManagedJobStatus.FAILED_CONTROLLER}, timeout=60)
    assert status == ManagedJobStatus.SUCCEEDED
    job = jobs_state.get_job(job_id)
    assert job["recovery_count"] >= 1
    assert marker.read_text().strip() == "2"


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_cancel():
    task = Task("mj-cancel", run="sleep 120")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=True, controller="local")
    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    cancelled = jobs.cancel([job_id])
    assert cancelled == [job_id]
    status = _wait_status(
        job_id, {ManagedJobStatus.CANCELLED}, timeout=30)
    assert status == ManagedJobStatus.CANCELLED
    # Cluster torn down.
    from skypilot_tpu import global_user_state
    job = jobs_state.get_job(job_id)
    assert global_user_state.get_cluster_from_name(
        job["cluster_name"]) is None


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_pipeline_chain(tmp_path):
    """Two-task chain: runs in order, each on its own cluster."""
    out = tmp_path / "order.txt"
    t1 = Task("stage1", run=f"echo one >> {out}")
    t1.set_resources(_local_res())
    t2 = Task("stage2", run=f"echo two >> {out}")
    t2.set_resources(_local_res())
    with dag_lib.Dag(name="pipe") as d:
        d.add(t1)
        d.add(t2)
        d.add_edge(t1, t2)
    job_id = jobs.launch(d, detach=False)
    assert jobs_state.get_status(job_id) == ManagedJobStatus.SUCCEEDED
    assert out.read_text().split() == ["one", "two"]
    assert jobs_state.get_job(job_id)["task_index"] == 1


@pytest.mark.usefixtures("tmp_state_dir")
def test_finalize_status_does_not_clobber_terminal():
    """Finalizing a dead controller must not overwrite a terminal status
    the controller reached between snapshot and kill."""
    job_id = jobs_state.add_job("fin", "/dev/null", "local", 1)
    jobs_state.set_status(job_id, ManagedJobStatus.SUCCEEDED)
    assert not jobs_state.finalize_status(job_id,
                                          ManagedJobStatus.CANCELLED)
    assert jobs_state.get_status(job_id) == ManagedJobStatus.SUCCEEDED
    # A non-terminal job IS finalized.
    job_id2 = jobs_state.add_job("fin2", "/dev/null", "local", 1)
    jobs_state.set_status(job_id2, ManagedJobStatus.RUNNING)
    assert jobs_state.finalize_status(job_id2,
                                      ManagedJobStatus.CANCELLED)
    assert jobs_state.get_status(job_id2) == ManagedJobStatus.CANCELLED


@pytest.mark.usefixtures("tmp_state_dir")
def test_jobs_queue_lists_jobs():
    task = Task("mj-q", run="echo q")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=False)
    q = jobs_core.queue()
    assert [j["job_id"] for j in q] == [job_id]
    assert q[0]["job_name"] == "mj-q"
    assert jobs_core.queue(skip_finished=True) == []


@pytest.mark.usefixtures("tmp_state_dir")
def test_dag_yaml_roundtrip(tmp_path):
    from skypilot_tpu.utils import dag_utils
    t1 = Task("a", run="echo a", envs={"X": "1"})
    t1.set_resources(_local_res())
    t2 = Task("b", run="echo b", num_nodes=2)
    t2.set_resources(_local_res())
    with dag_lib.Dag(name="rt") as d:
        d.add(t1)
        d.add(t2)
        d.add_edge(t1, t2)
    path = tmp_path / "dag.yaml"
    dag_utils.dump_chain_dag_to_yaml(d, str(path))
    loaded = dag_utils.load_chain_dag_from_yaml(str(path))
    assert loaded.name == "rt"
    assert [t.name for t in loaded.topo_order()] == ["a", "b"]
    assert loaded.tasks[0].envs == {"X": "1"}
    assert loaded.tasks[1].num_nodes == 2
    assert loaded.is_chain()


# ------------------------------------------- local-mount translation (r2 #3)
@pytest.mark.usefixtures("tmp_state_dir")
def test_translate_local_mounts_rewrites_task(tmp_path):
    """workdir + local file_mounts become source-free bucket mounts;
    cloud URIs stay (reference: controller_utils.py:568)."""
    from skypilot_tpu.data.storage import Storage, StorageMode
    from skypilot_tpu.utils import controller_utils

    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "train.py").write_text("print('hi')")
    data = tmp_path / "data.txt"
    data.write_text("payload")

    task = Task("tr", run="cat train.py", workdir=str(wd))
    task.set_resources(_local_res())
    task.set_file_mounts({"/data/in.txt": str(data),
                          "/data/ref": "gs://public-bucket/x"})
    controller_utils.maybe_translate_local_file_mounts_and_sync_up(
        task, run_id="test-run-1")

    # Local paths are gone from the task. The single-FILE mount becomes
    # a bucket URI (downloaded file-to-file — a bucket MOUNT would turn
    # the dst into a directory); directory mounts become storage mounts.
    assert task.workdir is None
    assert set(task.file_mounts) == {"/data/ref", "/data/in.txt"}
    assert task.file_mounts["/data/ref"] == "gs://public-bucket/x"
    assert task.file_mounts["/data/in.txt"].startswith("local://")
    assert task.file_mounts["/data/in.txt"].endswith("/data.txt")
    assert set(task.storage_mounts) == {"~/stpu_workdir"}
    for sto in task.storage_mounts.values():
        assert isinstance(sto, Storage)
        assert sto.mode == StorageMode.COPY
        assert sto.source is None
        assert not sto.persistent
    # The buckets were uploaded while the paths existed.
    wd_store = task.storage_mounts["~/stpu_workdir"].store
    assert (wd_store.bucket_dir / "train.py").read_text() == "print('hi')"
    # The file-URI download command restores FILE semantics at dst.
    from skypilot_tpu.data import cloud_stores
    cmd = cloud_stores.get_storage_from_path(
        task.file_mounts["/data/in.txt"]).make_download_command(
            task.file_mounts["/data/in.txt"], "/tmp/x/in.txt")
    assert "cp -r" in cmd and "/tmp/x/in.txt" in cmd
    # And the task survives the YAML round-trip the controller does.
    cfg = task.to_yaml_config()
    rt = Task.from_yaml_config(cfg)
    assert set(rt.storage_mounts) == set(task.storage_mounts)
    assert rt.storage_mounts["~/stpu_workdir"].source is None
    assert rt.file_mounts["/data/in.txt"] == task.file_mounts["/data/in.txt"]


@pytest.mark.usefixtures("tmp_state_dir")
def test_preemption_recovery_restores_translated_workdir(tmp_path):
    """The r2 VERDICT done-criterion: a managed job with a LOCAL workdir
    is preempted; the recovered cluster still sees the workdir files —
    restored from the translated bucket, not from the client path (which
    is deleted after submission to prove it)."""
    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "payload.txt").write_text("from-the-bucket")
    marker = tmp_path / "attempts"
    out = tmp_path / "result.txt"
    # Attempt 1 sleeps (gets preempted); attempt 2 reads the restored
    # workdir file. run: executes under ~/stpu_workdir (COPY-mounted).
    task = Task("mj-wd", run=(
        f'n=$(cat {marker} 2>/dev/null || echo 0); '
        f'echo $((n+1)) > {marker}; '
        f'if [ "$n" -ge 1 ]; then cat payload.txt > {out}; '
        f'else sleep 120; fi'), workdir=str(wd))
    task.set_resources(_local_res(use_spot=True))
    job_id = jobs.launch(task, detach=True, controller="local")

    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    deadline = time.time() + 30
    while not marker.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert marker.exists()

    # Delete the client-local workdir: recovery must NOT depend on it.
    import shutil
    shutil.rmtree(wd)

    cluster_name = jobs_state.get_job(job_id)["cluster_name"]
    local_provider.simulate_preemption(cluster_name)

    status = _wait_status(
        job_id, {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                 ManagedJobStatus.FAILED_CONTROLLER}, timeout=60)
    assert status == ManagedJobStatus.SUCCEEDED
    assert out.read_text().strip() == "from-the-bucket"
