"""Managed jobs: launch, preemption recovery, user failure, cancel,
pipelines — all hermetic on the local provider.

Reference test analog: tests/test_jobs.py + the recovery paths that the
reference can only exercise in real-cloud smoke tests; our local provider's
simulate_preemption makes them unit-testable (SURVEY §4 takeaway).
"""
import os
import time

import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import jobs
from skypilot_tpu.jobs import core as jobs_core
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.jobs.state import ManagedJobStatus
from skypilot_tpu.provision import local as local_provider
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


@pytest.fixture(autouse=True)
def fast_poll(monkeypatch):
    monkeypatch.setenv("STPU_JOBS_POLL_SECONDS", "0.2")


def _local_res(**kw):
    return Resources(cloud="local", **kw)


def _wait_status(job_id, statuses, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = jobs_state.get_status(job_id)
        if st in statuses:
            return st
        time.sleep(0.1)
    raise TimeoutError(f"job {job_id} stuck at {st}, wanted {statuses}")


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_success_inline():
    task = Task("mj-ok", run="echo managed-ok")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=False)
    assert jobs_state.get_status(job_id) == ManagedJobStatus.SUCCEEDED
    job = jobs_state.get_job(job_id)
    assert job["recovery_count"] == 0
    # Task cluster must not outlive the job.
    from skypilot_tpu import global_user_state
    assert global_user_state.get_cluster_from_name(
        job["cluster_name"]) is None


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_user_failure_not_recovered():
    task = Task("mj-fail", run="exit 7")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=False)
    job = jobs_state.get_job(job_id)
    assert job["status"] == "FAILED"
    assert job["recovery_count"] == 0


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_preemption_recovery(tmp_path):
    """Preempt the cluster mid-run; the controller must relaunch and the
    second attempt succeeds (EAGER_NEXT_REGION default strategy)."""
    marker = tmp_path / "attempts"
    task = Task("mj-recover", run=(
        f'n=$(cat {marker} 2>/dev/null || echo 0); '
        f'echo $((n+1)) > {marker}; '
        f'if [ "$n" -ge 1 ]; then echo recovered-ok; else sleep 120; fi'))
    task.set_resources(_local_res(use_spot=True))
    job_id = jobs.launch(task, detach=True, controller="local")

    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    # Wait for attempt 1 to actually start (marker written).
    deadline = time.time() + 30
    while not marker.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert marker.exists()

    cluster_name = jobs_state.get_job(job_id)["cluster_name"]
    local_provider.simulate_preemption(cluster_name)

    status = _wait_status(
        job_id, {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                 ManagedJobStatus.FAILED_CONTROLLER}, timeout=60)
    assert status == ManagedJobStatus.SUCCEEDED
    job = jobs_state.get_job(job_id)
    assert job["recovery_count"] >= 1
    assert marker.read_text().strip() == "2"


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_job_cancel():
    task = Task("mj-cancel", run="sleep 120")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=True, controller="local")
    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    cancelled = jobs.cancel([job_id])
    assert cancelled == [job_id]
    status = _wait_status(
        job_id, {ManagedJobStatus.CANCELLED}, timeout=30)
    assert status == ManagedJobStatus.CANCELLED
    # Cluster torn down.
    from skypilot_tpu import global_user_state
    job = jobs_state.get_job(job_id)
    assert global_user_state.get_cluster_from_name(
        job["cluster_name"]) is None


@pytest.mark.usefixtures("tmp_state_dir")
def test_managed_pipeline_chain(tmp_path):
    """Two-task chain: runs in order, each on its own cluster."""
    out = tmp_path / "order.txt"
    t1 = Task("stage1", run=f"echo one >> {out}")
    t1.set_resources(_local_res())
    t2 = Task("stage2", run=f"echo two >> {out}")
    t2.set_resources(_local_res())
    with dag_lib.Dag(name="pipe") as d:
        d.add(t1)
        d.add(t2)
        d.add_edge(t1, t2)
    job_id = jobs.launch(d, detach=False)
    assert jobs_state.get_status(job_id) == ManagedJobStatus.SUCCEEDED
    assert out.read_text().split() == ["one", "two"]
    assert jobs_state.get_job(job_id)["task_index"] == 1


@pytest.mark.usefixtures("tmp_state_dir")
def test_finalize_status_does_not_clobber_terminal():
    """Finalizing a dead controller must not overwrite a terminal status
    the controller reached between snapshot and kill."""
    job_id = jobs_state.add_job("fin", "/dev/null", "local", 1)
    jobs_state.set_status(job_id, ManagedJobStatus.SUCCEEDED)
    assert not jobs_state.finalize_status(job_id,
                                          ManagedJobStatus.CANCELLED)
    assert jobs_state.get_status(job_id) == ManagedJobStatus.SUCCEEDED
    # A non-terminal job IS finalized.
    job_id2 = jobs_state.add_job("fin2", "/dev/null", "local", 1)
    jobs_state.set_status(job_id2, ManagedJobStatus.RUNNING)
    assert jobs_state.finalize_status(job_id2,
                                      ManagedJobStatus.CANCELLED)
    assert jobs_state.get_status(job_id2) == ManagedJobStatus.CANCELLED


@pytest.mark.usefixtures("tmp_state_dir")
def test_jobs_queue_lists_jobs():
    task = Task("mj-q", run="echo q")
    task.set_resources(_local_res())
    job_id = jobs.launch(task, detach=False)
    q = jobs_core.queue()
    assert [j["job_id"] for j in q] == [job_id]
    assert q[0]["job_name"] == "mj-q"
    assert jobs_core.queue(skip_finished=True) == []


@pytest.mark.usefixtures("tmp_state_dir")
def test_dag_yaml_roundtrip(tmp_path):
    from skypilot_tpu.utils import dag_utils
    t1 = Task("a", run="echo a", envs={"X": "1"})
    t1.set_resources(_local_res())
    t2 = Task("b", run="echo b", num_nodes=2)
    t2.set_resources(_local_res())
    with dag_lib.Dag(name="rt") as d:
        d.add(t1)
        d.add(t2)
        d.add_edge(t1, t2)
    path = tmp_path / "dag.yaml"
    dag_utils.dump_chain_dag_to_yaml(d, str(path))
    loaded = dag_utils.load_chain_dag_from_yaml(str(path))
    assert loaded.name == "rt"
    assert [t.name for t in loaded.topo_order()] == ["a", "b"]
    assert loaded.tasks[0].envs == {"X": "1"}
    assert loaded.tasks[1].num_nodes == 2
    assert loaded.is_chain()


# ------------------------------------------- local-mount translation (r2 #3)
@pytest.mark.usefixtures("tmp_state_dir")
def test_translate_local_mounts_rewrites_task(tmp_path):
    """workdir + local file_mounts become source-free bucket mounts;
    cloud URIs stay (reference: controller_utils.py:568)."""
    from skypilot_tpu.data.storage import Storage, StorageMode
    from skypilot_tpu.utils import controller_utils

    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "train.py").write_text("print('hi')")
    data = tmp_path / "data.txt"
    data.write_text("payload")

    task = Task("tr", run="cat train.py", workdir=str(wd))
    task.set_resources(_local_res())
    task.set_file_mounts({"/data/in.txt": str(data),
                          "/data/ref": "gs://public-bucket/x"})
    controller_utils.maybe_translate_local_file_mounts_and_sync_up(
        task, run_id="test-run-1")

    # Local paths are gone from the task. The single-FILE mount becomes
    # a bucket URI (downloaded file-to-file — a bucket MOUNT would turn
    # the dst into a directory); directory mounts become storage mounts.
    assert task.workdir is None
    assert set(task.file_mounts) == {"/data/ref", "/data/in.txt"}
    assert task.file_mounts["/data/ref"] == "gs://public-bucket/x"
    assert task.file_mounts["/data/in.txt"].startswith("local://")
    assert task.file_mounts["/data/in.txt"].endswith("/data.txt")
    assert set(task.storage_mounts) == {"~/stpu_workdir"}
    for sto in task.storage_mounts.values():
        assert isinstance(sto, Storage)
        assert sto.mode == StorageMode.COPY
        assert sto.source is None
        assert not sto.persistent
    # The buckets were uploaded while the paths existed.
    wd_store = task.storage_mounts["~/stpu_workdir"].store
    assert (wd_store.bucket_dir / "train.py").read_text() == "print('hi')"
    # The file-URI download command restores FILE semantics at dst.
    from skypilot_tpu.data import cloud_stores
    cmd = cloud_stores.get_storage_from_path(
        task.file_mounts["/data/in.txt"]).make_download_command(
            task.file_mounts["/data/in.txt"], "/tmp/x/in.txt")
    assert "cp -r" in cmd and "/tmp/x/in.txt" in cmd
    # And the task survives the YAML round-trip the controller does.
    cfg = task.to_yaml_config()
    rt = Task.from_yaml_config(cfg)
    assert set(rt.storage_mounts) == set(task.storage_mounts)
    assert rt.storage_mounts["~/stpu_workdir"].source is None
    assert rt.file_mounts["/data/in.txt"] == task.file_mounts["/data/in.txt"]


@pytest.mark.usefixtures("tmp_state_dir")
def test_preemption_recovery_restores_translated_workdir(tmp_path):
    """The r2 VERDICT done-criterion: a managed job with a LOCAL workdir
    is preempted; the recovered cluster still sees the workdir files —
    restored from the translated bucket, not from the client path (which
    is deleted after submission to prove it)."""
    wd = tmp_path / "wd"
    wd.mkdir()
    (wd / "payload.txt").write_text("from-the-bucket")
    marker = tmp_path / "attempts"
    out = tmp_path / "result.txt"
    # Attempt 1 sleeps (gets preempted); attempt 2 reads the restored
    # workdir file. run: executes under ~/stpu_workdir (COPY-mounted).
    task = Task("mj-wd", run=(
        f'n=$(cat {marker} 2>/dev/null || echo 0); '
        f'echo $((n+1)) > {marker}; '
        f'if [ "$n" -ge 1 ]; then cat payload.txt > {out}; '
        f'else sleep 120; fi'), workdir=str(wd))
    task.set_resources(_local_res(use_spot=True))
    job_id = jobs.launch(task, detach=True, controller="local")

    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    deadline = time.time() + 30
    while not marker.exists() and time.time() < deadline:
        time.sleep(0.1)
    assert marker.exists()

    # Delete the client-local workdir: recovery must NOT depend on it.
    import shutil
    shutil.rmtree(wd)

    cluster_name = jobs_state.get_job(job_id)["cluster_name"]
    local_provider.simulate_preemption(cluster_name)

    status = _wait_status(
        job_id, {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                 ManagedJobStatus.FAILED_CONTROLLER}, timeout=60)
    assert status == ManagedJobStatus.SUCCEEDED
    assert out.read_text().strip() == "from-the-bucket"


# ---------------------------------------- checkpoint/resume + jobs chaos
import pathlib
import signal
import subprocess
import sys
import textwrap

REPO_ROOT = str(pathlib.Path(__file__).resolve().parent.parent)


def _ckpt_task(tmp_path, total_steps=6, hang_at=3):
    """A python task that checkpoints through train/checkpoint.py into
    the controller-stamped $STPU_JOB_CKPT_DIR: attempt 1 hangs at
    ``hang_at`` (to be preempted there); a resumed attempt restores the
    latest step and runs to completion. Each attempt appends its start
    step to the attempts file — the proof of where resume picked up."""
    script = tmp_path / "ckpt_task.py"
    attempts = tmp_path / "attempts"
    script.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO_ROOT!r})
        import numpy as np
        from skypilot_tpu.train import checkpoint as ck
        d = os.environ["STPU_JOB_CKPT_DIR"]
        restored = ck.restore_latest(d)
        start = int(restored.tree["step"]) if restored else 0
        with open({str(attempts)!r}, "a") as f:
            f.write(f"{{start}}\\n")
        for step in range(start + 1, {total_steps} + 1):
            ck.save(d, step, {{"step": np.int64(step)}})
            if step == {hang_at} and start == 0:
                time.sleep(120)   # preempted here on attempt 1
        print("done at", {total_steps})
    """))
    task = Task("mj-ckpt", run=f"{sys.executable} {script}")
    task.set_resources(_local_res(use_spot=True))
    return task, attempts


def _wait_for(predicate, timeout=30, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {msg}")


@pytest.mark.usefixtures("tmp_state_dir")
def test_preemption_resumes_from_checkpoint(tmp_path):
    """Chaos acceptance: preempt mid-epoch → recovery relaunches with
    $STPU_JOB_CKPT_DIR intact → the task resumes from the last durable
    checkpoint (not step 0) and the job SUCCEEDEDs at the right step,
    with resume progress recorded in jobs state."""
    from skypilot_tpu.train import checkpoint as ck
    task, attempts = _ckpt_task(tmp_path, total_steps=6, hang_at=3)
    job_id = jobs.launch(task, detach=True, controller="local")

    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    ckpt_dir = None

    def _ckpt_at_3():
        nonlocal ckpt_dir
        job = jobs_state.get_job(job_id)
        ckpt_dir = job.get("ckpt_dir")
        return bool(ckpt_dir) and (ck.latest_step(ckpt_dir) or 0) >= 3
    _wait_for(_ckpt_at_3, timeout=30, msg="first attempt to reach step 3")

    cluster_name = jobs_state.get_job(job_id)["cluster_name"]
    local_provider.simulate_preemption(cluster_name)

    status = _wait_status(
        job_id, {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                 ManagedJobStatus.FAILED_CONTROLLER}, timeout=60)
    assert status == ManagedJobStatus.SUCCEEDED
    job = jobs_state.get_job(job_id)
    assert job["recovery_count"] >= 1
    # Attempt 1 started at 0; the relaunch resumed at 3, not 0.
    assert attempts.read_text().split() == ["0", "3"]
    # The job finished at the right step, and the controller recorded
    # the resume progress (`stpu jobs queue` CKPT column).
    assert ck.latest_step(job["ckpt_dir"]) == 6
    assert job["last_ckpt_step"] == 6


@pytest.mark.usefixtures("tmp_state_dir")
def test_controller_killed_mid_recovery_is_adopted(tmp_path,
                                                   monkeypatch):
    """Chaos acceptance: SIGKILL the controller while it is INSIDE a
    recovery; reconcile() spawns an adopting controller that finishes
    the interrupted recovery and the job reaches SUCCEEDED."""
    marker = tmp_path / "attempts"
    task = Task("mj-adopt", run=(
        f'n=$(cat {marker} 2>/dev/null || echo 0); '
        f'echo $((n+1)) > {marker}; '
        f'if [ "$n" -ge 1 ]; then echo adopted-ok; else sleep 120; fi'))
    task.set_resources(_local_res(use_spot=True))
    # Delay rule targeting ONLY the recovery relaunch (skip=1 passes
    # the initial launch through), giving a wide window to kill the
    # controller mid-recovery. The controller process arms it from the
    # inherited environment.
    monkeypatch.setenv("STPU_FAULTS",
                       "jobs.launch:delay:s=5,skip=1,times=1")
    job_id = jobs.launch(task, detach=True, controller="local")

    _wait_status(job_id, {ManagedJobStatus.RUNNING}, timeout=30)
    _wait_for(marker.exists, timeout=30, msg="attempt 1 start")
    pid = jobs_state.get_job(job_id)["controller_pid"]
    assert pid

    cluster_name = jobs_state.get_job(job_id)["cluster_name"]
    local_provider.simulate_preemption(cluster_name)
    _wait_status(job_id, {ManagedJobStatus.RECOVERING}, timeout=30)

    # The controller is in the injected 5s delay inside recover():
    # kill it there — the classic half-finished recovery. (The test
    # process is the controller's parent, so it lingers as a zombie —
    # the adoption machinery must treat that as dead.)
    from skypilot_tpu.jobs import controller as controller_mod
    os.kill(pid, signal.SIGKILL)
    _wait_for(lambda: not controller_mod._pid_alive(pid), timeout=10,
              msg="controller death")

    # The adopter must not inherit the chaos rule.
    monkeypatch.delenv("STPU_FAULTS")
    from skypilot_tpu.jobs import core as jc
    adopted = jc.reconcile(detach=True)
    assert adopted == [job_id]

    status = _wait_status(
        job_id, {ManagedJobStatus.SUCCEEDED, ManagedJobStatus.FAILED,
                 ManagedJobStatus.FAILED_CONTROLLER}, timeout=60)
    assert status == ManagedJobStatus.SUCCEEDED
    job = jobs_state.get_job(job_id)
    assert job["recovery_count"] >= 1
    assert marker.read_text().strip() == "2"
    assert job["controller_pid"] != pid
    # Nothing left to adopt.
    assert jc.reconcile(detach=True) == []


@pytest.mark.usefixtures("tmp_state_dir")
def test_reconcile_skips_live_controllers_and_refuses_double_adopt():
    """reconcile() must never adopt a job whose controller is alive,
    and run_controller(adopt=True) refuses a live pid outright."""
    from skypilot_tpu import exceptions as exc
    from skypilot_tpu.jobs import controller as controller_mod
    from skypilot_tpu.jobs import core as jc
    job_id = jobs_state.add_job("live", "/dev/null", "local", 1)
    jobs_state.set_status(job_id, ManagedJobStatus.RUNNING)
    # A stand-in live controller: liveness checks require the cmdline
    # to look like a jobs controller (pid-reuse guard), so carry the
    # marker in argv.
    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(60)",
                             "jobs.controller-standin"])
    try:
        jobs_state.set_controller_pid(job_id, proc.pid)
        assert jc.reconcile(detach=True) == []
        with pytest.raises(exc.SkyTpuError, match="live controller"):
            controller_mod.run_controller(job_id, "/dev/null",
                                          adopt=True)
    finally:
        proc.kill()
        proc.wait()


@pytest.mark.usefixtures("tmp_state_dir")
def test_gang_host_fault_fails_job_not_hangs(tmp_path, monkeypatch):
    """gang.host chaos seam: a host dying at start-of-run fails the
    gang (and the managed job) cleanly instead of hanging the slice."""
    task = Task("mj-gang-host", run="echo should-not-run",
                num_nodes=2)
    task.set_resources(_local_res())
    # The seam lives in the per-host wrapper (a subprocess): it arms
    # from the inherited environment.
    monkeypatch.setenv("STPU_FAULTS", "gang.host:raise")
    job_id = jobs.launch(task, detach=False)
    monkeypatch.delenv("STPU_FAULTS")
    assert jobs_state.get_status(job_id) == ManagedJobStatus.FAILED


@pytest.mark.usefixtures("tmp_state_dir")
def test_claim_controller_cas_single_winner():
    """Two reconcilers observing the same dead pid: exactly one CAS
    claim wins (the concurrency guard behind reconcile())."""
    job_id = jobs_state.add_job("cas", "/dev/null", "local", 1)
    jobs_state.set_status(job_id, ManagedJobStatus.RUNNING)
    jobs_state.set_controller_pid(job_id, 99999999)  # dead
    assert jobs_state.claim_controller(job_id, 99999999, -111)
    # The loser (same expectation) must not win.
    assert not jobs_state.claim_controller(job_id, 99999999, -222)
    # NULL expectation CAS also works (job that never recorded a pid).
    job_id2 = jobs_state.add_job("cas2", "/dev/null", "local", 1)
    assert jobs_state.claim_controller(job_id2, None, -111)
    assert not jobs_state.claim_controller(job_id2, None, -222)
