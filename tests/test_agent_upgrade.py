"""Agent runtime versioning: stamp on bring-up, re-ship on reused
clusters, daemon self-exit on version drift, env secrets over stdin.

VERDICT r3 missing #2 (reference: sky/skylet/attempt_skylet.py:42-47
restarts skylet on version mismatch) + ADVICE r3 finding #1
(CommandRunner argv exposed task env secrets via ps).
"""
import subprocess

import pytest

from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.agent import daemon as daemon_lib
from skypilot_tpu.provision import provisioner
from skypilot_tpu.utils import command_runner as runner_lib
from skypilot_tpu.utils import wheel_utils


# ------------------------------------------------------------- re-ship
class _StubHandle:
    provider_name = "gcp"
    cluster_name = "reuse-test"
    cluster_info = None

    def __init__(self, runner):
        self._runner = runner

    def get_command_runners(self):
        return [self._runner]


class _StampRunner:
    def __init__(self, stamp, transport_dead=False):
        self.stamp = stamp
        self.transport_dead = transport_dead

    def run(self, cmd, require_outputs=False, **kw):
        assert "runtime_version" in cmd
        if self.transport_dead:
            return (255, "", "ssh: connect timed out")
        if self.stamp is None:   # the || echo fallback in the probe
            return (0, "__UNSTAMPED__\n", "")
        return (0, self.stamp + "\n", "")


@pytest.mark.usefixtures("tmp_state_dir")
@pytest.mark.parametrize("remote_stamp,expect_reship", [
    ("current", False),      # matches local version -> no-op
    ("deadbeef00000000", True),  # drifted -> re-ship
    (None, True),            # pre-upgrade cluster, unstamped -> re-ship
])
def test_reuse_reships_on_version_drift(monkeypatch, remote_stamp,
                                        expect_reship):
    from skypilot_tpu.backends import slice_backend
    local = wheel_utils.runtime_version()
    stamp = local if remote_stamp == "current" else remote_stamp
    calls = []
    monkeypatch.setattr(provisioner, "setup_agent_runtime",
                        lambda info, identity=None: calls.append(info))
    backend = slice_backend.SliceBackend()
    monkeypatch.setattr(slice_backend.SliceBackend, "_cluster_identity",
                        lambda self, handle: {})
    backend._ensure_agent_runtime(_StubHandle(_StampRunner(stamp)))
    assert bool(calls) == expect_reship


@pytest.mark.usefixtures("tmp_state_dir")
def test_reuse_transport_failure_is_not_unstamped(monkeypatch):
    """A dead transport (rc 255) must raise a clear error, NOT trigger a
    full re-ship against an unreachable cluster."""
    from skypilot_tpu import exceptions as exc
    from skypilot_tpu.backends import slice_backend
    calls = []
    monkeypatch.setattr(provisioner, "setup_agent_runtime",
                        lambda info, identity=None: calls.append(info))
    backend = slice_backend.SliceBackend()
    with pytest.raises(exc.CommandError, match="could not reach head"):
        backend._ensure_agent_runtime(
            _StubHandle(_StampRunner(None, transport_dead=True)))
    assert calls == []


@pytest.mark.usefixtures("tmp_state_dir")
def test_setup_agent_runtime_writes_version_stamp(tmp_path, monkeypatch):
    dirs = {}

    def fake_ssh_runner(info, inst):
        host_dir = tmp_path / inst.instance_id
        dirs[inst.instance_id] = host_dir
        return runner_lib.LocalCommandRunner(inst.instance_id,
                                             str(host_dir))

    monkeypatch.setattr(provisioner, "_ssh_runner", fake_ssh_runner)
    monkeypatch.setattr(provisioner, "_RUNTIME_INSTALL_CMD", "true")
    from skypilot_tpu.provision.common import ClusterInfo, InstanceInfo
    info = ClusterInfo(
        cluster_name="stamp-test", provider_name="gcp",
        region="r", zone="z",
        instances={"h0": InstanceInfo(
            instance_id="h0", internal_ip="10.0.0.1", external_ip=None,
            slice_id="s0", host_index=0, tags={})},
        head_instance_id="h0", provider_config={})
    provisioner.setup_agent_runtime(info, {"cluster_name": "stamp-test"})
    stamp = (dirs["h0"] / ".stpu_agent" / "runtime_version").read_text()
    assert stamp == wheel_utils.runtime_version()


# ------------------------------------------------- daemon version drift
def test_daemon_exits_on_version_drift(tmp_path):
    d = daemon_lib.Daemon(home=str(tmp_path), interval=0.01)
    stamp_path = tmp_path / ".stpu_agent" / "runtime_version"
    # No stamp: never stale.
    assert not d.runtime_stale()
    # Matching stamp: not stale.
    stamp_path.write_text(d._my_version)
    assert not d.runtime_stale()
    # Drifted stamp: stale only after TWO consecutive ticks (one tick of
    # slack for the bring-up window where the new daemon boots just
    # before the stamp lands).
    stamp_path.write_text("somethingelse0000")
    assert not d.runtime_stale()
    assert d.runtime_stale()
    # Stamp restored mid-count: counter resets.
    stamp_path.write_text(d._my_version)
    assert not d.runtime_stale()
    stamp_path.write_text("somethingelse0000")
    assert not d.runtime_stale()


def test_agent_start_cmd_replaces_daemon(tmp_path):
    """_AGENT_START_CMD kills the pidfile'd predecessor (a re-ship must
    not leave two daemons racing over the job DB)."""
    agent_dir = tmp_path / ".stpu_agent"
    agent_dir.mkdir()
    victim = subprocess.Popen(["sleep", "300"])
    (agent_dir / "daemon.pid").write_text(str(victim.pid))
    # Run only the replace prelude of the start command (not the nohup
    # daemon launch itself).
    prelude = daemon_cmd = provisioner._AGENT_START_CMD.split("nohup")[0]
    assert "daemon.pid" in prelude
    subprocess.run(["bash", "-c", prelude + "true"], check=True,
                   env={"HOME": str(tmp_path), "PATH": "/usr/bin:/bin"})
    assert victim.wait(timeout=5) == -15  # SIGTERM
    assert not (agent_dir / "daemon.pid").exists()


# ------------------------------------------------- env secrets -> stdin
def _capture_runs(monkeypatch):
    calls = []

    def fake_run(argv, **kw):
        stdin = kw.get("stdin")
        body = stdin.read().decode() if stdin is not None else ""
        calls.append((argv, body))

        class P:
            returncode = 0
            stdout = ""
            stderr = ""
        return P()

    monkeypatch.setattr(runner_lib.subprocess, "run", fake_run)
    monkeypatch.setattr(
        runner_lib, "_run_with_log",
        lambda argv, stdin=None, **kw: (
            calls.append((argv, stdin.read().decode()
                          if stdin is not None else "")), 0)[1])
    return calls


def test_ssh_runner_env_rides_stdin(monkeypatch):
    calls = _capture_runs(monkeypatch)
    r = runner_lib.SSHCommandRunner("h0", "1.2.3.4", ssh_user="u",
                                    ssh_key_path="/dev/null")
    r.run("echo hi", env={"WANDB_API_KEY": "hunter2secret"},
          require_outputs=True)
    r.run("echo hi", env={"WANDB_API_KEY": "hunter2secret"})
    for argv, body in calls:
        joined = " ".join(argv)
        assert "hunter2secret" not in joined, "secret leaked to argv"
        assert "bash --login -s" in joined
        assert "export WANDB_API_KEY=hunter2secret" in body
        assert "echo hi" in body


def test_kubectl_runner_env_rides_stdin(monkeypatch):
    calls = _capture_runs(monkeypatch)
    r = runner_lib.KubernetesCommandRunner("h0", pod_name="p",
                                           namespace="ns")
    r.run("echo hi", env={"TOKEN": "sekrit123"}, require_outputs=True)
    argv, body = calls[0]
    assert "sekrit123" not in " ".join(argv)
    assert "-i" in argv  # stdin-interactive exec
    assert "export TOKEN=sekrit123" in body


def test_env_free_commands_keep_argv_form(monkeypatch):
    """Without env there is no secret to hide: the plain -c argv path
    (streamable, no stdin plumbing) is preserved."""
    calls = _capture_runs(monkeypatch)
    r = runner_lib.SSHCommandRunner("h0", "1.2.3.4", ssh_user="u",
                                    ssh_key_path="/dev/null")
    r.run("echo hi", require_outputs=True)
    argv, body = calls[0]
    assert any("bash --login -c" in a for a in argv)
    assert body == ""
