"""Cross-cloud transfer + storage CLI group (hermetic: local stores and
a mocked Storage Transfer Service; reference analog:
sky/data/data_transfer.py:39 + sky/cli.py:3852)."""
import json

import pytest
from click.testing import CliRunner

from skypilot_tpu import cli as cli_mod
from skypilot_tpu import core, exceptions, global_user_state
from skypilot_tpu.data import data_transfer
from skypilot_tpu.data import storage as storage_lib


@pytest.mark.usefixtures("tmp_state_dir")
def test_local_transfer_and_registry(tmp_path):
    src_dir = tmp_path / "data"
    src_dir.mkdir()
    (src_dir / "a.txt").write_text("payload")
    st = storage_lib.Storage(name="bkt-src", source=str(src_dir),
                             store="local")
    st.sync()
    assert [r["name"] for r in core.storage_ls()] == ["bkt-src"]

    data_transfer.transfer("local", "bkt-src", "local", "bkt-dst")
    from skypilot_tpu.utils import paths
    assert (paths.home() / "buckets" / "bkt-dst" / "a.txt"
            ).read_text() == "payload"

    with pytest.raises(exceptions.NotSupportedError, match="route"):
        data_transfer.transfer("gcs", "a", "local", "b")
    with pytest.raises(exceptions.StorageError, match="not found"):
        data_transfer.local_to_local("missing", "x")


@pytest.mark.usefixtures("tmp_state_dir")
def test_s3_to_gcs_via_fake_sts(monkeypatch):
    """The STS flow: create job -> poll operations -> done."""
    calls = []

    def fake_rest(method, path, body=None):
        calls.append((method, path))
        if method == "POST" and path == "transferJobs":
            assert body["transferSpec"]["awsS3DataSource"][
                "bucketName"] == "src-s3"
            assert body["transferSpec"]["gcsDataSink"][
                "bucketName"] == "dst-gcs"
            return {"name": "transferJobs/12345"}
        if method == "GET" and path.startswith("transferOperations"):
            done = len(calls) > 2  # first poll: running; second: done
            return {"operations": [{"done": done}]}
        raise AssertionError(f"unexpected call {method} {path}")

    monkeypatch.setattr(data_transfer, "rest", fake_rest)
    data_transfer.s3_to_gcs(
        "src-s3", "dst-gcs", project_id="proj",
        aws_access_key_id="AK", aws_secret_access_key="SK",
        poll_seconds=0.01)
    assert calls[0] == ("POST", "transferJobs")
    assert len(calls) >= 3


@pytest.mark.usefixtures("tmp_state_dir")
def test_s3_to_gcs_propagates_operation_error(monkeypatch):
    def fake_rest(method, path, body=None):
        if method == "POST":
            return {"name": "transferJobs/x"}
        return {"operations": [
            {"done": True, "error": {"code": 7, "message": "denied"}}]}

    monkeypatch.setattr(data_transfer, "rest", fake_rest)
    with pytest.raises(exceptions.StorageError, match="denied"):
        data_transfer.s3_to_gcs("a", "b", project_id="p",
                                aws_access_key_id="AK",
                                aws_secret_access_key="SK",
                                poll_seconds=0.01)


@pytest.mark.usefixtures("tmp_state_dir")
def test_storage_cli_ls_delete_transfer(tmp_path):
    src_dir = tmp_path / "d"
    src_dir.mkdir()
    (src_dir / "f").write_text("x")
    storage_lib.Storage(name="bkt-cli", source=str(src_dir),
                        store="local").sync()

    runner = CliRunner()
    out = runner.invoke(cli_mod.cli, ["storage", "ls"])
    assert out.exit_code == 0 and "bkt-cli" in out.output

    out = runner.invoke(cli_mod.cli, [
        "storage", "transfer", "local://bkt-cli", "local://bkt2"])
    assert out.exit_code == 0, out.output

    out = runner.invoke(cli_mod.cli,
                        ["storage", "delete", "bkt-cli", "--yes"])
    assert out.exit_code == 0, out.output
    assert core.storage_ls() == []
    from skypilot_tpu.utils import paths
    assert not (paths.home() / "buckets" / "bkt-cli").exists()

    out = runner.invoke(cli_mod.cli,
                        ["storage", "delete", "nope", "--yes"])
    assert out.exit_code != 0
