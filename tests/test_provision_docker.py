"""Docker provisioner against a hermetic fake docker CLI.

Reference analog: sky/backends/local_docker_backend.py (the
single-container dev path), tested the way test_provision_kubernetes
tests pods: an in-memory daemon behind the provision.docker.docker()
seam — no docker binary anywhere.
"""
import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import docker as docker_provider


class FakeDocker:
    def __init__(self):
        self.containers = {}   # name -> {"State", "Labels"}
        self.calls = []

    def __call__(self, args):
        self.calls.append(tuple(args))
        verb = args[0]
        if verb == "run":
            name = args[args.index("--name") + 1]
            labels = {}
            for i, a in enumerate(args):
                if a == "--label":
                    k, v = args[i + 1].split("=", 1)
                    labels[k] = v
            self.containers[name] = {"Names": name, "State": "running",
                                     "Labels": ",".join(
                                         f"{k}={v}"
                                         for k, v in labels.items())}
            return name
        if verb == "ps":
            sel = args[args.index("--filter") + 1]
            _, kv = sel.split("=", 1)
            key, val = kv.split("=", 1)
            return [c for c in self.containers.values()
                    if f"{key}={val}" in c["Labels"]]
        if verb == "start":
            self.containers[args[1]]["State"] = "running"
            return []
        if verb == "stop":
            self.containers[args[1]]["State"] = "exited"
            return []
        if verb == "rm":
            self.containers.pop(args[-1], None)
            return []
        raise AssertionError(f"unexpected docker verb: {args}")


@pytest.fixture
def fake(monkeypatch):
    fd = FakeDocker()
    monkeypatch.setattr(docker_provider, "docker", fd)
    return fd


def test_run_creates_labeled_container(fake):
    rec = docker_provider.run_instances(
        None, None, "c1", {"image": "my/img:1"})
    assert rec.head_instance_id == "stpu-c1-s0-h0"
    c = fake.containers["stpu-c1-s0-h0"]
    assert "stpu-cluster=c1" in c["Labels"]
    assert any("my/img:1" in " ".join(call) for call in fake.calls)


def test_query_and_info(fake):
    docker_provider.run_instances(None, None, "c1", {})
    assert docker_provider.query_instances("c1", {}) == {
        "stpu-c1-s0-h0": "running"}
    info = docker_provider.get_cluster_info(None, "c1", {})
    assert info.provider_name == "docker"
    assert info.head_instance_id == "stpu-c1-s0-h0"
    inst = info.ordered_instances()[0]
    assert inst.tags["container"] == "stpu-c1-s0-h0"


def test_stop_start_cycle(fake):
    docker_provider.run_instances(None, None, "c1", {})
    docker_provider.stop_instances("c1", {})
    assert docker_provider.query_instances("c1", {}) == {
        "stpu-c1-s0-h0": "stopped"}
    rec = docker_provider.run_instances(None, None, "c1", {})
    assert rec.created_instance_ids == []  # restarted, not recreated
    assert docker_provider.query_instances("c1", {}) == {
        "stpu-c1-s0-h0": "running"}


def test_terminate_removes(fake):
    docker_provider.run_instances(None, None, "c1", {})
    docker_provider.run_instances(None, None, "other", {})
    docker_provider.terminate_instances("c1", {})
    assert set(fake.containers) == {"stpu-other-s0-h0"}


def test_docker_capabilities_and_runner():
    from skypilot_tpu import clouds as clouds_lib
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.utils.command_runner import DockerCommandRunner

    cloud = clouds_lib.get_cloud("docker")
    F = clouds_lib.CloudImplementationFeatures
    res = Resources(cloud="docker")
    unsupported = cloud.unsupported_features_for_resources(res)
    assert F.MULTI_NODE in unsupported  # single-container dev path
    assert F.STOP not in unsupported    # containers CAN stop
    assert res.is_launchable and res.hourly_price() == 0.0

    runner = DockerCommandRunner("n0", container="stpu-c1-s0-h0")
    argv = runner._exec_argv(interactive=True)
    assert argv[:3] == ["docker", "exec", "-i"]
    assert "stpu-c1-s0-h0" in argv


def test_multihost_docker_rejected(fake):
    with pytest.raises(exceptions.ProvisionError, match="ONE container"):
        docker_provider.run_instances(None, None, "c1",
                                      {"hosts_per_slice": 2})
    assert fake.containers == {}
