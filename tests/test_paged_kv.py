"""Paged KV-cache block pool: one device-resident pool for slots +
prefix cache, zero-copy shared-prefix aliasing.

The contract under test, strongest first:

  * paged decode is BIT-IDENTICAL to the dense path — greedy and
    seeded sampling, all three families, across slot reuse and chunked
    prefill (the block-table gather feeds the same online-softmax tile
    as the dense slice, so aligned tiles produce the same floats);
  * a prefix hit is a block-table entry write: zero splice copies on
    the hot path (the dense splice entry points no longer exist), and
    publish-on-free is a refcount transfer;
  * block refcount/aliasing lifecycle: shared blocks survive a
    mid-stream cancel, eviction never frees a pinned block, and 500
    seeded admit/cancel cycles leak nothing;
  * admission is pool-capacity based — a request longer than the dense
    per-slot row is admitted when its blocks fit — and under the SAME
    KV budget the paged engine sustains strictly more concurrent
    slots than dense for mixed-length traffic;
  * KV-cache donation is preserved through both paged jitted entry
    points (single-device and TP-sharded), and the same admission
    sequence reproduces the same block tables on every gang host.
"""
import dataclasses
import random
import socket
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import gemma, llama, mixtral
from skypilot_tpu.serve import decode_engine
from skypilot_tpu.serve import gang_replica
from skypilot_tpu.serve import kv_pool
from skypilot_tpu.serve.decode_engine import DecodeEngine, EngineError


def _tiny(family="llama"):
    if family == "mixtral":
        return mixtral, mixtral.MixtralConfig.tiny()
    if family == "gemma":
        return gemma, gemma.GemmaConfig.tiny(vocab_size=128)
    return llama, llama.LlamaConfig.tiny(vocab_size=128)


def _drive(engine, rounds=200):
    """Step an UNSTARTED engine deterministically until idle."""
    for _ in range(rounds):
        engine._admit()
        did = engine._prefill_one()
        did = engine._decode_step() or did
        if not did and not engine._waiting:
            return
    raise AssertionError("engine did not quiesce")


# ==================================================== pool accounting
def test_block_pool_accounting_and_errors():
    pool = kv_pool.BlockPool(6, 8)           # block 0 scratch, 5 usable
    assert pool.usable_blocks == 5
    assert pool.blocks_for(1) == 1 and pool.blocks_for(17) == 3
    pool.reserve(3)
    assert pool.available() == 2
    blocks = [pool.alloc() for _ in range(3)]
    assert 0 not in blocks                   # scratch never allocated
    assert pool.available() == 2             # reservation consumed
    pool.retain(blocks[0])
    pool.release(blocks[0])
    assert pool.refcount(blocks[0]) == 1     # still one owner
    pool.release(blocks[0])
    assert pool.refcount(blocks[0]) == 0     # freed
    with pytest.raises(RuntimeError, match="double-release"):
        pool.release(blocks[0])
    with pytest.raises(RuntimeError, match="available"):
        pool.reserve(5)
    pool.release(blocks[1])
    pool.release(blocks[2])
    assert pool.free_blocks() == 5


def test_paged_trie_lru_refcount_and_interior_protection():
    """Paged eviction contract, mirroring the dense pool test: LRU
    leaves go first, pinned nodes are never evicted, and an interior
    chunk outlives fresher leaves until its children are gone."""
    pool = kv_pool.BlockPool(8, 4)
    trie = kv_pool.PagedPrefixCache(pool, chunk=4)
    a, b = list(range(10, 14)), list(range(20, 24))

    def adopt(prompt, n_tokens):
        owned = [pool.alloc(reserved=False)
                 for _ in range(n_tokens // 4)]
        trie.publish(prompt, n_tokens, lambda j: owned[j])
        for blk in owned:                    # slot's own ref drops
            pool.release(blk)

    adopt(a + b + [1], 8)                    # chain a -> b
    adopt(list(range(30, 34)) + [1], 4)      # c
    assert trie.stats()["chunks"] == 3
    assert pool.free_blocks() == 7 - 3

    held = trie.match(a + b + [1])
    assert len(held) == 2
    trie.pin(held)
    assert all(n.refs == 1 for n in held)

    # Evict: the unpinned LRU leaf (c) goes; the pinned chain and the
    # interior node survive any number of attempts.
    assert trie.evict_one()
    keys = {n.key for n in trie.nodes()}
    assert tuple(a) in keys and tuple(b) in keys
    assert tuple(range(30, 34)) not in keys
    assert not trie.evict_one()              # only pinned/interior left
    assert {n.key for n in trie.nodes()} == {tuple(a), tuple(b)}

    trie.unpin(held)
    assert trie.evict_one()                  # leaf b first
    assert {n.key for n in trie.nodes()} == {tuple(a)}
    assert trie.evict_one()                  # then a, now a leaf
    assert pool.free_blocks() == 7


# ================================================= bit-parity: engine
def test_paged_engine_matches_dense_and_reference():
    """5 ragged greedy requests through 2 slots: paged streams equal
    the dense engine's AND the fixed-path decode token-for-token —
    slot reuse, chunked prefill, and the block-table gather all
    covered by one workload."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    rng = random.Random(0)
    specs = [([rng.randint(1, 127) for _ in range(rng.randint(1, 19))],
              rng.randint(1, 8)) for _ in range(5)]

    def run(paged):
        eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                           prefill_chunk=8, paged=paged).start()
        try:
            reqs = [eng.submit(p, max_tokens=mt) for p, mt in specs]
            return [r.result(timeout=300.0) for r in reqs]
        finally:
            eng.shutdown()

    dense, paged = run(False), run(True)
    assert dense == paged
    for (p, mt), got in zip(specs, paged):
        ref = mdl.decode(cfg, params, jnp.asarray([p], jnp.int32),
                         jnp.int32(len(p)), mt, len(p) + mt)
        assert got == [int(t) for t in ref[0]], (p, mt)


@pytest.mark.parametrize("family", ["mixtral", "gemma"])
def test_paged_parity_other_families(family):
    """The block-table decode path holds bit-identically for the MoE
    (dense-routed) and MQA/tied-head families too."""
    mdl, cfg = _tiny(family)
    params = mdl.init(cfg, jax.random.key(0))
    rng = random.Random(3)
    specs = [([rng.randint(1, cfg.vocab_size - 1)
               for _ in range(rng.randint(2, 18))],
              rng.randint(1, 6)) for _ in range(3)]

    def run(paged):
        eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                           prefill_chunk=8, paged=paged).start()
        try:
            reqs = [eng.submit(p, max_tokens=mt) for p, mt in specs]
            return [r.result(timeout=300.0) for r in reqs]
        finally:
            eng.shutdown()

    assert run(False) == run(True)


def test_paged_seeded_sampling_parity_and_zero_copy_hit():
    """temperature > 0 streams are bit-identical dense vs paged, AND
    the paged repeat of the same prompt — a zero-copy aliased hit —
    still samples the identical stream (the aliased blocks hold the
    exact rows prefill would recompute)."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    prompt = [int(t) for t in jax.random.randint(
        jax.random.key(3), (21,), 1, 128)]

    def run(paged):
        eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                           prefill_chunk=8, paged=paged).start()
        try:
            first = eng.submit(prompt, max_tokens=6, temperature=0.9,
                               seed=17).result(timeout=300.0)
            second_req = eng.submit(prompt, max_tokens=6,
                                    temperature=0.9, seed=17)
            second = second_req.result(timeout=300.0)
            return first, second, second_req.cached_prompt_tokens
        finally:
            eng.shutdown()

    d1, d2, _ = run(False)
    p1, p2, cached = run(True)
    assert d1 == d2 == p1 == p2
    assert cached == 16                      # 2 aliased 8-token blocks


# ========================================== zero-copy on the hot path
def test_paged_prefix_hit_zero_copies_on_hot_path():
    """Under paging a prefix hit performs NO splice work: the dense
    splice entry points (_insert_chunk/_gather_chunk and the per-model
    gather/insert_cache_rows) are RETIRED — asserted gone, so nothing
    can quietly reintroduce a copy path — and the warm request must
    still restore its prefix (table aliasing) and publish on free
    (refcount transfer)."""
    for retired in ("_insert_chunk", "_gather_chunk", "PrefixCache"):
        assert not hasattr(decode_engine, retired), retired
    for mod in (llama, mixtral, gemma):
        for retired in ("gather_cache_rows", "insert_cache_rows"):
            assert not hasattr(mod, retired), (mod.__name__, retired)
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True).start()
    try:
        shared = [int(t) for t in jax.random.randint(
            jax.random.key(11), (17,), 1, 128)]
        cold = eng.submit(shared + [5, 6], max_tokens=4)
        cold_toks = cold.result(timeout=300.0)
        warm = eng.submit(shared + [7, 8, 9], max_tokens=4)
        warm_toks = warm.result(timeout=300.0)
        for prompt, got in ((shared + [5, 6], cold_toks),
                            (shared + [7, 8, 9], warm_toks)):
            ref = mdl.decode(cfg, params, jnp.asarray([prompt]),
                             jnp.int32(len(prompt)), 4,
                             len(prompt) + 4)
            assert got == [int(t) for t in ref[0]]
        assert cold.cached_prompt_tokens == 0
        assert warm.cached_prompt_tokens == 16
        assert warm.prefill_chunks < cold.prefill_chunks
        stats = eng.prefix_cache.stats()
        assert stats["zero_copy_hits"] >= 1
        assert stats["tokens_saved"] >= 16
    finally:
        eng.shutdown()


# ======================================== admission: pool, not row
def test_paged_admission_pool_bound_not_row_length():
    """The dense engine rejects len(prompt) + max_tokens > max_seq.
    Under paging the bound is POOL capacity: the same request is
    admitted when its blocks fit (and still decodes correctly), while
    a request bigger than the whole pool gets the pool-bound error."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    prompt = [int(t) for t in jax.random.randint(
        jax.random.key(5), (70,), 1, 128)]

    dense = DecodeEngine(cfg, params, slots=2, max_seq=64,
                         prefill_chunk=8)
    with pytest.raises(EngineError, match="exceeds the engine cache"):
        dense.submit(prompt, max_tokens=8)

    # 32 usable blocks x 8 tokens = 256 logical tokens per request.
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True,
                       kv_pool_blocks=33).start()
    try:
        got = eng.submit(prompt, max_tokens=8).result(timeout=300.0)
        ref = mdl.decode(cfg, params, jnp.asarray([prompt], jnp.int32),
                         jnp.int32(70), 8, 78)
        assert got == [int(t) for t in ref[0]]
        with pytest.raises(EngineError, match="exceeds the KV pool"):
            eng.submit(list(range(1, 260)), max_tokens=16)
    finally:
        eng.shutdown()


# =============================================== aliasing lifecycle
def test_paged_aliasing_cancel_mid_stream_blocks_survive():
    """Two slots aliasing one cached prefix; one cancels mid-stream.
    The shared blocks must survive (the other slot still reads them
    through its table), eviction must refuse to touch them while
    pinned, and the survivor's stream stays token-identical."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True)
    shared = [int(t) for t in jax.random.randint(
        jax.random.key(9), (17,), 1, 128)]
    # Cold leg publishes the two full prompt chunks on free.
    first = eng.submit(shared, max_tokens=1)
    _drive(eng)
    assert first.result(timeout=5.0)
    assert eng.prefix_cache.stats()["chunks"] == 2

    a = eng.submit(shared + [3, 4, 5], max_tokens=6)
    b = eng.submit(shared + [6, 7, 8], max_tokens=6)
    eng._admit()
    pinned = [n for n in eng.prefix_cache.nodes() if n.refs > 0]
    assert len(pinned) == 2 and all(n.refs == 2 for n in pinned)
    shared_blocks = {n.block for n in pinned}
    assert all(eng._pool.refcount(blk) == 3 for blk in shared_blocks)

    # A few interleaved steps so both are mid-stream, then cancel one.
    for _ in range(4):
        eng._prefill_one()
        eng._decode_step()
    a.cancel()
    _drive(eng)
    try:
        a.result(timeout=5.0)
    except EngineError:
        pass                                # cancelled is clean either way
    # Shared blocks survived the cancel and pinning blocked eviction
    # throughout; the survivor's stream equals the fixed path.
    keys = {n.key for n in eng.prefix_cache.nodes()}
    assert {n.key for n in pinned} <= keys
    got = b.result(timeout=5.0)
    ref = mdl.decode(cfg, params, jnp.asarray([shared + [6, 7, 8]]),
                     jnp.int32(20), 6, 26)
    assert got == [int(t) for t in ref[0]]
    assert all(n.refs == 0 for n in eng.prefix_cache.nodes())


def test_paged_release_idempotent_500_cycle_churn():
    """500 seeded admit/cancel cycles (cancel at random prefill/decode
    depth): slot-level release is idempotent under refcounted blocks,
    so the accounting identity free + trie == usable holds at the end
    with zero reservations and zero pins outstanding."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True)
    rng = random.Random(7)
    for _ in range(500):
        prompt = [rng.randint(1, 127)
                  for _ in range(rng.randint(9, 30))]
        req = eng.submit(prompt, max_tokens=rng.randint(1, 4))
        eng._admit()
        for _ in range(rng.randint(0, 5)):
            did = eng._prefill_one()
            did = eng._decode_step() or did
            if not did:
                break
        req.cancel()
        _drive(eng)
    pool = eng._pool
    trie_blocks = len(eng.prefix_cache.nodes())
    assert all(s.request is None for s in eng._slots)
    assert pool.free_blocks() + trie_blocks == pool.usable_blocks
    assert pool._reserved == 0
    assert all(n.refs == 0 for n in eng.prefix_cache.nodes())


# ============================================== capacity per KV byte
def test_paged_more_live_slots_than_dense_same_budget():
    """Same KV budget (128 cache-token rows): dense fits 2 max_seq=64
    rows; the paged pool runs 6 slots over the identical byte budget
    and admission packs by ACTUAL length — a mixed short-request burst
    sustains strictly more concurrent slots."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    rng = random.Random(4)
    specs = [([rng.randint(1, 127) for _ in range(8)], 4)
             for _ in range(6)]

    dense = DecodeEngine(cfg, params, slots=2, max_seq=64,
                         prefill_chunk=8)
    for p, mt in specs:
        dense.submit(p, max_tokens=mt)
    _drive(dense)

    paged = DecodeEngine(cfg, params, slots=6, max_seq=64,
                         prefill_chunk=8, paged=True,
                         kv_pool_blocks=128 // 8 + 1)
    for p, mt in specs:
        paged.submit(p, max_tokens=mt)
    _drive(paged)

    assert dense.peak_live_slots == 2
    assert paged.peak_live_slots > dense.peak_live_slots
    # Same tokens either way — capacity, not correctness, changed.
    assert paged.peak_live_slots == 6


# ===================================================== donation + TP
def test_paged_entry_points_keep_donation_sharded_and_single():
    """The pool stays donated through BOTH paged jitted entry points —
    single-device and TP-sharded (cache_shardings applies unchanged to
    the pool layout) — so the O(layers * blocks) buffer updates in
    place instead of double-buffering HBM. Pinned per family."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    mesh = mesh_lib.make_mesh({"tp": 2}, devices=jax.devices()[:2])
    rules = mesh_lib.DEFAULT_RULES
    for family in ("llama", "mixtral", "gemma"):
        mdl, cfg = _tiny(family)
        cfg = dataclasses.replace(cfg, dtype=jnp.float32)
        for shard in (False, True):
            params = mdl.init(cfg, jax.random.key(0))
            pool = mdl.init_paged_cache(cfg, 8, 8)
            if shard:
                params = gang_replica.shard_params(cfg, params, mesh,
                                                   rules)
                shardings = gang_replica.cache_shardings(cfg, mesh,
                                                         rules)
                # shardings also carries k_scale/v_scale for the int8
                # pool; a bf16 pool has no such leaves — filter like
                # the engine does.
                pool = jax.device_put(
                    pool, {k: shardings[k] for k in pool})
            table = jnp.ones((2, 8), jnp.int32)
            old_k, old_v = pool["k"], pool["v"]
            buf = jnp.zeros((8,), jnp.int32).at[:4].set(
                jnp.asarray([1, 2, 3, 4]))
            _logits, pool = decode_engine._paged_prefill_chunk(
                cfg, params, pool, buf, table[0], jnp.int32(0),
                jnp.int32(4), jnp.int32(1), 64)
            assert old_k.is_deleted() and old_v.is_deleted(), \
                f"{family} shard={shard}: prefill dropped donation"
            old_k, old_v = pool["k"], pool["v"]
            _nxt, pool = decode_engine._paged_step(
                cfg, params, pool, jnp.zeros((2,), jnp.int32),
                jnp.asarray([4, 0], jnp.int32), table, 64,
                jnp.zeros((2,), jnp.float32),
                jnp.zeros((2,), jnp.uint32))
            assert old_k.is_deleted() and old_v.is_deleted(), \
                f"{family} shard={shard}: step dropped donation"


def test_paged_tp_engine_bit_identical_to_dense_single():
    """The TP paged engine (params by param_specs, POOL by the same
    cache_specs sharding, tp=2 mesh) reproduces the single-process
    DENSE engine bit-identically in f32 — the full parity chain
    paged+sharded == dense+unsharded, greedy and seeded."""
    cfg = dataclasses.replace(llama.LlamaConfig.tiny(vocab_size=128),
                              dtype=jnp.float32)
    params = llama.init(cfg, jax.random.key(0))
    topo = gang_replica.ReplicaTopology(hosts=1, ici_axes={"tp": 2})
    mesh, rules = gang_replica.build_mesh(topo)
    sparams = gang_replica.shard_params(cfg, params, mesh, rules)
    reqs = [([1, 2, 3, 4, 5], 8, 0.0, 0),
            ([7, 9, 11], 10, 0.8, 123),
            ([4] * 70, 6, 0.0, 0),          # chunked prefill path
            ([5, 6], 8, 1.1, 7)]

    def run(engine):
        out = []
        try:
            handles = [engine.submit(p, max_tokens=mt,
                                     temperature=t, seed=s)
                       for p, mt, t, s in reqs]
            for h in handles:
                out.append(h.result(timeout=600.0))
        finally:
            engine.shutdown()
        return out

    ref = run(DecodeEngine(cfg, params, slots=2, max_seq=128).start())
    tp_paged = run(DecodeEngine(cfg, sparams, slots=2, max_seq=128,
                                mesh=mesh, rules=rules,
                                paged=True).start())
    assert tp_paged == ref


# ============================================ gang lockstep + config
def test_paged_same_admission_sequence_same_block_tables():
    """The follower-mirror property paging adds to the gang contract:
    two engines fed the identical admission sequence step-for-step
    allocate identical block tables AND produce identical streams —
    pool state is a pure function of the (mirrored) request order."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    rng = random.Random(6)
    seq = [([rng.randint(1, 127) for _ in range(rng.randint(4, 20))],
            rng.randint(1, 5)) for _ in range(8)]

    def run():
        eng = DecodeEngine(cfg, params, slots=3, max_seq=64,
                           prefill_chunk=8, paged=True)
        reqs = [eng.submit(p, max_tokens=mt) for p, mt in seq]
        tables = []
        for _ in range(400):
            eng._admit()
            tables.append(eng._table.copy())
            did = eng._prefill_one()
            did = eng._decode_step() or did
            if not did and not eng._waiting:
                break
        return [r.result(timeout=5.0) for r in reqs], tables

    toks_a, tables_a = run()
    toks_b, tables_b = run()
    assert toks_a == toks_b
    assert len(tables_a) == len(tables_b)
    for ta, tb in zip(tables_a, tables_b):
        np.testing.assert_array_equal(ta, tb)


def test_kv_geometry_single_derivation_no_drift():
    """resolve_kv_geometry IS what the engine runs: the handshake dict
    serve_llm computes equals DecodeEngine.kv_config() for the same
    inputs — including the auto-sized pool, which raw STPU_KV_* knobs
    cannot express (two hosts with identical knobs but different slot
    counts auto-size DIFFERENT pools; the effective dict catches it)."""
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    eng = DecodeEngine(cfg, params, slots=3, max_seq=64,
                       prefill_chunk=8, paged=True)
    geo = decode_engine.resolve_kv_geometry(
        slots=3, max_seq=64, prefill_chunk=8, paged=True)
    assert eng.kv_config() == geo
    assert geo["pool_blocks"] == 3 * (64 // 8) + 1
    # Same knobs, different slot count -> different effective pool.
    other = decode_engine.resolve_kv_geometry(
        slots=4, max_seq=64, prefill_chunk=8, paged=True)
    assert other != geo


def test_gang_welcome_carries_kv_config_and_mismatch_kills_follower():
    """The leader stamps its EFFECTIVE KV geometry into every
    follower's welcome and a disagreeing follower dies at join (rc 1)
    instead of silently running a differently-sized pool out of
    lockstep."""
    topo = gang_replica.ReplicaTopology(hosts=2)
    kv = decode_engine.resolve_kv_geometry(
        slots=4, max_seq=64, prefill_chunk=8, paged=True)
    leader = gang_replica.GangLeader(topo, port=0, kv_config=kv)
    try:
        # Raw peek: welcome carries the kv block verbatim.
        import json as json_lib
        sock = socket.create_connection(("127.0.0.1", leader.port),
                                        timeout=5.0)
        wf, rf = sock.makefile("wb"), sock.makefile("rb")
        gang_replica._send_line(wf, {"op": "hello", "rank": 1,
                                     "pid": 1})
        welcome = json_lib.loads(rf.readline())
        assert welcome["kv"] == kv
        sock.close()

        class _StubEngine:
            def start(self):
                return self

            def shutdown(self):
                pass

        rc_box = []

        def follower():
            # Identical raw knobs, different slot count: the effective
            # geometry differs (auto-sized pool), and must be fatal.
            rc_box.append(gang_replica.follower_serve(
                _StubEngine, topo, f"127.0.0.1:{leader.port}", rank=1,
                kv_config=decode_engine.resolve_kv_geometry(
                    slots=8, max_seq=64, prefill_chunk=8,
                    paged=True)))

        t = threading.Thread(target=follower, daemon=True)
        t.start()
        t.join(timeout=30.0)
        assert rc_box == [1]
    finally:
        leader.shutdown()


# ==================================================== metrics surface
def test_paged_pool_metrics_exposed():
    """Pool gauges and the zero-copy counter land in the process
    registry (and therefore the replica /metrics + LB merge)."""
    from skypilot_tpu.observability import metrics as metrics_lib
    mdl, cfg = _tiny()
    params = mdl.init(cfg, jax.random.key(0))
    zero_before = metrics_lib.REGISTRY.counter(
        "stpu_engine_prefix_zero_copy_hits_total").get()
    eng = DecodeEngine(cfg, params, slots=2, max_seq=64,
                       prefill_chunk=8, paged=True).start()
    try:
        shared = list(range(1, 18))
        eng.submit(shared, max_tokens=2).result(timeout=300.0)
        eng.submit(shared + [19], max_tokens=2).result(timeout=300.0)
    finally:
        eng.shutdown()
    assert metrics_lib.REGISTRY.counter(
        "stpu_engine_prefix_zero_copy_hits_total").get() > zero_before
    text = metrics_lib.render()
    assert "stpu_engine_kv_pool_blocks_total" in text
    assert "stpu_engine_kv_pool_blocks_free" in text
    assert "stpu_engine_kv_pool_blocks_pinned" in text
