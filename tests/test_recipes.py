"""Recipe tree tests: convergence in-process, checkpoint/resume, and the
2-node DDP recipe end-to-end on the local provider — the first real
consumer of the SKYPILOT_COORDINATOR_ADDR env contract (reference analog:
the smoke tests running examples/torch_ddp_benchmark on real clouds)."""
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from skypilot_tpu import execution
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


def test_mnist_converges():
    from skypilot_tpu.recipes import mnist
    metrics = mnist.main(["--steps", "120"])
    assert metrics["test_accuracy"] > 0.8


def test_glue_imdb_converges():
    from skypilot_tpu.recipes import glue_imdb
    # Converged (0.99+ deterministic) well before 80 steps; 160 only
    # doubled the tier-1 wall time.
    metrics = glue_imdb.main(["--steps", "80"])
    assert metrics["test_accuracy"] > 0.75


def test_mixtral_ep_recipe_runs():
    from skypilot_tpu.recipes import mixtral_ep
    metrics = mixtral_ep.main(["--steps", "2", "--batch-size", "2",
                               "--seq-len", "32"])
    assert metrics["final_loss"] > 0
    # The ep axis actually sharded over the virtual 8-device mesh.
    assert metrics["mesh"]["ep"] > 1


def test_llama_lora_checkpoint_resume(tmp_path):
    from skypilot_tpu.recipes import llama_lora
    ck = str(tmp_path / "ck")
    m1 = llama_lora.main(["--model", "tiny", "--steps", "6",
                          "--save-every", "3", "--batch-size", "2",
                          "--seq-len", "32", "--checkpoint-dir", ck])
    assert m1["resumed_from"] == 0
    assert m1["lora_params"] > 0
    # Relaunch (the preemption-recovery shape): picks up at step 6.
    m2 = llama_lora.main(["--model", "tiny", "--steps", "10",
                          "--save-every", "3", "--batch-size", "2",
                          "--seq-len", "32", "--checkpoint-dir", ck])
    assert m2["resumed_from"] == 6


def test_serve_llm_endpoints():
    import jax
    from skypilot_tpu.models import llama
    from skypilot_tpu.recipes import serve_llm

    cfg = llama.LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.PRNGKey(0))
    httpd = serve_llm.serve(cfg, params, 0)  # ephemeral port
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        deadline = time.time() + 120
        status = None
        while time.time() < deadline:
            try:
                status = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/health", timeout=2).status
                break
            except urllib.error.HTTPError as e:
                status = e.code  # 503 while warming
            except OSError:
                pass
            time.sleep(0.5)
        assert status == 200

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_tokens": 4}
                            ).encode())
        out = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert len(out["tokens"]) == 4
        # Sampling path: valid token ids, seeded deterministically.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_tokens": 4,
                             "temperature": 0.8, "seed": 7}).encode())
        out1 = json.loads(urllib.request.urlopen(req, timeout=120).read())
        out2 = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert out1["tokens"] == out2["tokens"]
        assert all(0 <= t < cfg.vocab_size for t in out1["tokens"])
        # Bad request -> 400, not a crash.
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=b'{"nope": 1}')
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=10)
        assert err.value.code == 400
    finally:
        httpd.shutdown()


@pytest.mark.usefixtures("tmp_state_dir")
def test_resnet_ddp_two_nodes_end_to_end():
    """Launch the DDP recipe on 2 local-provider hosts: each host process
    reads the env contract, rank 1 connects to rank 0's coordination
    service, gradients are mean-allreduced every step, and both ranks end
    with bit-identical params."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    task = Task(
        "ddp2", num_nodes=2,
        run=(f"{sys.executable} -m skypilot_tpu.recipes.resnet_ddp "
             f"--steps 3 --tiny --batch-size 4 --out-file ~/ddp_out.json"),
        envs={"PYTHONPATH": repo_root, "JAX_PLATFORMS": "cpu",
              # Pytest's conftest exports an 8-device XLA_FLAGS; the host
              # processes model 1 device per host.
              "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    task.set_resources(Resources(cloud="local"))
    job_id, handle = execution.launch(task, cluster_name="t-ddp",
                                      detach_run=True, stream_logs=False)

    from skypilot_tpu.agent import job_lib
    from skypilot_tpu.backends import slice_backend
    backend = slice_backend.SliceBackend()
    deadline = time.time() + 180
    while time.time() < deadline:
        st = backend.job_status(handle, job_id)
        if st and job_lib.JobStatus(st).is_terminal():
            break
        time.sleep(0.5)
    assert st == "SUCCEEDED", backend.job_status(handle, job_id)

    digests = []
    for inst in handle.cluster_info.ordered_instances():
        out = json.load(open(inst.tags["host_dir"] + "/ddp_out.json"))
        assert out["num_nodes"] == 2
        digests.append(out["param_digest"])
    assert digests[0] == digests[1]
