"""`stpu loadgen`: trace-driven load harness + SLO reports (ISSUE 7).

The stories pinned here:
  * the same (spec, seed) expands to a BIT-identical request schedule
    — and a full run against a stub LB stack replays it (equal
    schedule digests), while the SLO report carries TTFT/TPOT/e2e
    percentiles, achieved-vs-offered QPS, and goodput-under-SLO;
  * the run-scoped scraper snapshots /metrics into a JSONL time
    series beside the report, parseable back through promtext;
  * an injected engine slowdown (fault-injection delay mode) degrades
    the reported goodput and is flagged by bench_compare on the new
    serving-leg metrics with the right polarity;
plus the satellites: the promtext render→parse→render golden
round-trip, Histogram.quantile interpolation, the latency-tuned TTFT
buckets, and LB inflight-accounting / PrefixAffinity bounded-load
spill under a seeded loadgen burst.
"""
import importlib.util
import json
import math
import pathlib
import socket
import threading
import time
import urllib.request
import http.server
import socketserver

import pytest

from skypilot_tpu.benchmark import loadgen
from skypilot_tpu.observability import metrics
from skypilot_tpu.observability import promtext
from skypilot_tpu.serve import load_balancer as lb_lib
from skypilot_tpu.serve.load_balancing_policies import (
    PrefixAffinityPolicy, RoundRobinPolicy)
from skypilot_tpu.utils import fault_injection as fi

_SPEC = importlib.util.spec_from_file_location(
    "bench_compare",
    pathlib.Path(__file__).parent.parent / "tools" / "bench_compare.py")
bench_compare = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_compare)


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


# ============================================================ schedule
def test_schedule_bit_identical_same_seed():
    spec = loadgen.LoadSpec(mix="chat", arrival="poisson", qps=25,
                            duration_s=2.0, seed=7)
    s1, s2 = loadgen.build_schedule(spec), loadgen.build_schedule(spec)
    assert s1 == s2
    assert loadgen.schedule_digest(s1) == loadgen.schedule_digest(s2)
    other = loadgen.build_schedule(
        loadgen.LoadSpec(mix="chat", qps=25, duration_s=2.0, seed=8))
    assert loadgen.schedule_digest(other) != loadgen.schedule_digest(s1)


@pytest.mark.parametrize("mix", loadgen.MIXES)
@pytest.mark.parametrize("arrival", loadgen.ARRIVALS)
def test_schedule_shapes(mix, arrival):
    spec = loadgen.LoadSpec(mix=mix, arrival=arrival, qps=15,
                            duration_s=2.0, seed=3)
    sched = loadgen.build_schedule(spec)
    assert sched, f"{mix}/{arrival} produced an empty schedule"
    ats = [r.at for r in sched]
    assert ats == sorted(ats)
    assert all(0 < at < spec.duration_s for at in ats)
    assert all(1 <= len(r.prompt) <= spec.max_prompt_tokens
               for r in sched)
    assert all(1 <= r.max_tokens <= spec.max_tokens for r in sched)


def test_chat_mix_shares_prefixes_across_requests_and_seeds():
    spec = loadgen.LoadSpec(mix="chat", qps=30, duration_s=2.0, seed=1)
    sched = loadgen.build_schedule(spec)
    heads = {r.prompt[:spec.shared_prefix] for r in sched}
    assert 1 < len(heads) <= spec.n_prefixes
    # Prefix identity depends on the seed only, not qps/duration: a
    # cache warmed by one trace shape is warm for another.
    other = loadgen.build_schedule(loadgen.LoadSpec(
        mix="chat", qps=5, duration_s=1.0, seed=1))
    assert {r.prompt[:spec.shared_prefix] for r in other} <= set(
        tuple(p) for p in map(tuple, heads)) | heads


def test_long_context_mix_is_prefill_heavy():
    chat = loadgen.build_schedule(
        loadgen.LoadSpec(mix="chat", qps=20, duration_s=2.0, seed=2))
    lctx = loadgen.build_schedule(loadgen.LoadSpec(
        mix="long_context", qps=20, duration_s=2.0, seed=2))
    avg = lambda s: sum(len(r.prompt) for r in s) / len(s)  # noqa: E731
    assert avg(lctx) > 3 * avg(chat)


def test_bursty_mix_modulates_rate():
    spec = loadgen.LoadSpec(mix="bursty", arrival="uniform", qps=10,
                            duration_s=4.0, seed=0, burst_factor=6.0,
                            burst_period_s=4.0)
    sched = loadgen.build_schedule(spec)
    # Crest of the wave (mid-period) must be denser than the troughs.
    mid = sum(1 for r in sched if 1.0 <= r.at < 3.0)
    edges = len(sched) - mid
    assert mid > 2 * edges


def test_spec_validation():
    with pytest.raises(ValueError):
        loadgen.LoadSpec(mix="nope").validate()
    with pytest.raises(ValueError):
        loadgen.LoadSpec(arrival="nope").validate()
    with pytest.raises(ValueError):
        loadgen.LoadSpec(qps=0).validate()


# ============================================================ promtext
def test_promtext_roundtrip_golden():
    """render → parse → render recovers the exact document, and the
    parsed samples carry the exact values (the shared parser the
    loadgen scraper, bench gates, and `stpu metrics` consumers rely
    on)."""
    reg = metrics.Registry()
    c = reg.counter("rt_total", "Req.", ("method", "code"))
    c.labels(method="GET", code="200").inc(3)
    c.labels(method="POST", code="502").inc()
    g = reg.gauge("rt_gauge", "G.", ("k",))
    g.labels(k='a"b\\c\nd').set(-math.inf)
    g.labels(k="frac").set(0.125)
    h = reg.histogram("rt_seconds", "L.", ("svc",), buckets=(0.1, 1.0))
    h.labels(svc="x").observe(0.05)
    h.labels(svc="x").observe(7.0)
    text = reg.render()
    fams = promtext.parse(text)
    assert promtext.render_families(fams) == text
    assert fams["rt_total"].kind == "counter"
    assert fams["rt_seconds"].kind == "histogram"
    assert promtext.value(fams, "rt_total", method="GET",
                          code="200") == 3
    assert promtext.value(fams, "rt_gauge", k='a"b\\c\nd') == -math.inf
    assert promtext.value(fams, "rt_gauge", k="frac") == 0.125
    assert promtext.counter_total(fams, "rt_total") == 4
    # Parse is the exact inverse on a second round trip too.
    assert promtext.render_families(
        promtext.parse(promtext.render_families(fams))) == text


def test_promtext_histogram_snapshot_delta_and_quantile():
    reg = metrics.Registry()
    h = reg.histogram("d_seconds", "D.", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    first = promtext.histogram(promtext.parse(reg.render()),
                               "d_seconds")
    for v in (3.0, 3.0, 5.0):
        h.observe(v)
    last = promtext.histogram(promtext.parse(reg.render()),
                              "d_seconds")
    assert last.count == 7
    run_window = last.delta(first)
    assert run_window.count == 3
    assert run_window.cumulative == [0.0, 0.0, 2.0, 3.0]
    # Scraped quantile == live-registry quantile (shared math).
    assert last.quantile(0.5) == pytest.approx(h.quantile(0.5))
    # A quantile landing in +Inf returns the top finite bound.
    assert run_window.quantile(0.99) == 4.0


def test_promtext_parse_errors_and_labeled_aggregation():
    with pytest.raises(promtext.ParseError):
        promtext.parse("bad line without value\n# TYPE x counter")
    reg = metrics.Registry()
    h = reg.histogram("agg_seconds", "A.", ("code",), buckets=(1.0,))
    h.labels(code="200").observe(0.5)
    h.labels(code="502").observe(2.0)
    fams = promtext.parse(reg.render())
    merged = promtext.histogram(fams, "agg_seconds")
    assert merged.count == 2 and merged.cumulative == [1.0, 2.0]
    only_200 = promtext.histogram(fams, "agg_seconds", code="200")
    assert only_200.count == 1 and only_200.sum == 0.5


# ============================================================ quantile
def test_histogram_quantile_interpolation():
    reg = metrics.Registry()
    h = reg.histogram("q_seconds", "Q.", buckets=(1.0, 2.0, 4.0))
    assert math.isnan(h.quantile(0.5))          # empty
    for v in (0.5, 1.5, 1.5, 3.0):
        h.observe(v)
    # rank 2 of 4 is halfway through the (1, 2] bucket's 2 counts.
    assert h.quantile(0.5) == pytest.approx(1.5)
    # First bucket interpolates from 0.
    assert 0.0 < h.quantile(0.1) < 1.0
    assert h.quantile(1.0) == pytest.approx(4.0)


def test_quantile_from_cumulative_inf_bucket():
    # Observation beyond the top bound: quantile saturates at the
    # highest finite bound rather than inventing a number.
    val = metrics.quantile_from_cumulative(
        [1.0, 2.0], [0, 0, 5], 0.99)
    assert val == 2.0
    assert math.isnan(metrics.quantile_from_cumulative([1.0], [0, 0],
                                                       0.5))


def test_engine_ttft_buckets_latency_tuned():
    """Satellite: the engine TTFT histograms use the SLO-grade bucket
    set (DEFAULT_BUCKETS collapses 1-30s tails into 2.5-20s-wide
    buckets), and the exposition stays backward-compatible: same
    family names, same _bucket/_sum/_count sample shape."""
    from skypilot_tpu.serve import decode_engine
    assert decode_engine._TTFT.buckets == metrics.LATENCY_BUCKETS
    assert decode_engine._PREFIX_TTFT.buckets == metrics.LATENCY_BUCKETS
    # Tail band resolution: at least 8 bounds between 0.1s and 20s.
    in_band = [b for b in metrics.LATENCY_BUCKETS if 0.1 <= b <= 20.0]
    assert len(in_band) >= 8
    # Delta against the current state: the process-wide registry may
    # already hold TTFT observations from other suites in a full run.
    before = promtext.histogram(promtext.parse(metrics.render()),
                                "stpu_engine_ttft_seconds")
    decode_engine._TTFT.observe(0.45)
    text = metrics.render()
    assert "# TYPE stpu_engine_ttft_seconds histogram" in text
    snap = promtext.histogram(promtext.parse(text),
                              "stpu_engine_ttft_seconds")
    assert snap is not None and snap.count >= 1
    assert snap.bounds == list(metrics.LATENCY_BUCKETS)
    window = snap.delta(before) if before is not None else snap
    assert window.count == 1
    # 0.45 lands in the (0.4, 0.6] bucket — resolvable to that band.
    assert 0.4 <= window.quantile(0.5) <= 0.6


# ====================================================== stub LB stack
class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True

    def handle_error(self, request, client_address):
        pass


class _SSEHandler(http.server.BaseHTTPRequestHandler):
    """Stub replica: streams min(max_tokens, cap) SSE token events
    with a per-token delay, then [DONE] — the serve_llm contract the
    loadgen client parses. ``hits``/``delay``/``abort_after`` are
    class attributes set per test. Observes into the engine TTFT
    histogram so the LB scrape path carries real server-side data."""
    protocol_version = "HTTP/1.1"
    hits = None
    delay = 0.002
    token_cap = 6
    abort_after = None        # tokens, then drop the connection

    def log_message(self, *a):
        pass

    def do_GET(self):
        if self.path == "/metrics":
            body = metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", metrics.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Length", "2")
        self.end_headers()
        self.wfile.write(b"{}")

    def do_POST(self):
        from skypilot_tpu.serve import decode_engine
        length = int(self.headers.get("Content-Length") or 0)
        req = json.loads(self.rfile.read(length) or b"{}")
        if self.hits is not None:
            self.hits.append(self.server.server_address[1])
        t0 = time.perf_counter()
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        n = min(int(req.get("max_tokens", 4)), self.token_cap)
        for i in range(n):
            time.sleep(self.delay)
            if i == 0:
                decode_engine._TTFT.observe(time.perf_counter() - t0)
            if self.abort_after is not None and i >= self.abort_after:
                # Mid-stream death: no [DONE], no terminator.
                self.wfile.flush()
                self.connection.close()
                return
            lb_lib.write_chunk(
                self.wfile, f'data: {{"token": {i}}}\n\n'.encode())
        lb_lib.write_chunk(self.wfile, b"data: [DONE]\n\n")
        lb_lib.end_chunks(self.wfile)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _start_replica(handler_cls):
    server = _Server(("127.0.0.1", 0), handler_cls)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{server.server_address[1]}"


def _start_lb(policy, **handler_attrs):
    port = _free_port()
    handler = type("Handler", (lb_lib._ProxyHandler,), {
        "policy": policy, "recorder": lb_lib.RequestRecorder(),
        "breaker": lb_lib.CircuitBreaker(), **handler_attrs})
    server = lb_lib._ThreadingHTTPServer(("127.0.0.1", port), handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"http://127.0.0.1:{port}"


# ================================================== e2e smoke (tier-1)
def test_loadgen_e2e_bit_identical_and_slo_report(tmp_state_dir,
                                                  tmp_path):
    """Acceptance: two runs with the same seed against the same stub
    stack produce a bit-identical schedule, and the report carries
    percentiles, achieved-vs-offered QPS, goodput, and the scraped
    server-side series."""
    replica, _ = _start_replica(
        type("Ok", (_SSEHandler,), {"delay": 0.002}))
    policy = RoundRobinPolicy()
    policy.set_ready_replicas(
        [f"http://127.0.0.1:{replica.server_address[1]}"])
    lb, target = _start_lb(policy)
    spec = loadgen.LoadSpec(mix="chat", qps=20, duration_s=1.5, seed=11,
                            max_tokens=6)
    try:
        rep1 = loadgen.run(target, spec, slo_ttft_s=1.0, slo_tpot_s=0.5,
                           scrape_interval=0.25,
                           out_dir=str(tmp_path / "run1"))
        rep2 = loadgen.run(target, spec, slo_ttft_s=1.0, slo_tpot_s=0.5,
                           scrape_interval=0.25,
                           out_dir=str(tmp_path / "run2"))
    finally:
        lb.shutdown()
        replica.shutdown()
    assert rep1["schedule_sha256"] == rep2["schedule_sha256"]
    assert rep1["requests"]["scheduled"] == rep2["requests"]["scheduled"]
    assert rep1["requests"]["ok"] == rep1["requests"]["scheduled"]
    assert rep1["goodput"]["fraction"] == 1.0
    for key in ("ttft", "tpot", "e2e"):
        assert rep1["latency_s"][key]["p99"] is not None
    assert rep1["qps"]["offered"] > 0
    assert 0 < rep1["qps"]["achieved"] <= rep1["qps"]["offered"] * 2
    assert rep1["tokens"]["generated"] > 0
    # Server-side: the engine TTFT histogram (scraped via the LB merge)
    # yields interpolated percentiles for the run window.
    assert rep1["server"]["scrapes"] >= 2
    assert rep1["server"]["engine_ttft"]["p99"] > 0
    # Artifacts: schedule + report + the JSONL metric time series.
    run_dir = pathlib.Path(rep1["out_dir"])
    sched_doc = json.loads((run_dir / "schedule.json").read_text())
    assert sched_doc["digest"] == rep1["schedule_sha256"]
    report_doc = json.loads((run_dir / "report.json").read_text())
    assert report_doc["goodput"]["fraction"] == 1.0
    series = [json.loads(line) for line in
              (run_dir / "metrics.jsonl").read_text().splitlines()]
    assert len(series) >= 2
    assert any("families" in rec and
               "stpu_lb_requests_total" in rec["families"]
               for rec in series)
    # The rendered report mentions the headline numbers.
    text = loadgen.format_report(rep1)
    assert "goodput" in text and "achieved" in text


def test_loadgen_fault_delay_degrades_goodput_and_gates(tmp_state_dir,
                                                        tmp_path):
    """Acceptance: an injected upstream slowdown (fault-injection
    delay mode at the lb.upstream seam) measurably degrades goodput
    and p99 TTFT, and bench_compare flags BOTH new serving-leg metrics
    with the right polarity."""
    replica, _ = _start_replica(
        type("Ok2", (_SSEHandler,), {"delay": 0.002}))
    policy = RoundRobinPolicy()
    policy.set_ready_replicas(
        [f"http://127.0.0.1:{replica.server_address[1]}"])
    lb, target = _start_lb(policy)
    spec = loadgen.LoadSpec(mix="chat", qps=15, duration_s=1.2, seed=4,
                            max_tokens=4)
    try:
        base = loadgen.run(target, spec, slo_ttft_s=0.3,
                           scrape_interval=0.3,
                           out_dir=str(tmp_path / "base"))
        slow = loadgen.run(target, spec, slo_ttft_s=0.3,
                           scrape_interval=0.3,
                           out_dir=str(tmp_path / "slow"),
                           faults="lb.upstream:delay:s=0.8",
                           faults_at=0.0)
    finally:
        lb.shutdown()
        replica.shutdown()
    assert not fi.ENABLED        # the run cleared its own arming
    assert base["goodput"]["fraction"] == 1.0
    assert slow["goodput"]["fraction"] < 0.5
    assert slow["latency_s"]["ttft"]["p99"] > \
        base["latency_s"]["ttft"]["p99"] + 0.5

    def bench_doc(report):
        return {"value": 50.0, "detail": {"serving": {
            "llama_slo_goodput": report["goodput"]["fraction"],
            "llama_p99_ttft_s": report["latency_s"]["ttft"]["p99"],
            "llama_loadgen_tok_s": report["tokens"]["tok_s"],
        }}}

    _, regressions = bench_compare.compare(
        bench_doc(base), bench_doc(slow),
        list(bench_compare.DEFAULT_METRICS), 5.0,
        lower_patterns=list(bench_compare.DEFAULT_METRICS_LOWER))
    joined = "\n".join(regressions)
    assert "llama_slo_goodput" in joined          # dropped: regression
    assert "llama_p99_ttft_s" in joined           # rose: regression
    # And the polarity is honest: the un-regressed direction passes.
    _, none = bench_compare.compare(
        bench_doc(slow), bench_doc(base),
        list(bench_compare.DEFAULT_METRICS), 5.0,
        lower_patterns=list(bench_compare.DEFAULT_METRICS_LOWER))
    assert not [r for r in none if "p99_ttft" in r
                or "slo_goodput" in r]


# ==================================== LB accounting under burst (sat.)
def test_lb_inflight_returns_slots_under_burst_failures(tmp_state_dir,
                                                        tmp_path):
    """Satellite: report_done returns the in-flight slot on EVERY exit
    path — clean streams, retried dead-replica attempts, mid-stream
    aborts, and 413 rejections — under a seeded open-loop burst, so
    least-loaded accounting can never leak a slot."""
    good, good_url = _start_replica(
        type("Good", (_SSEHandler,), {"delay": 0.002}))
    flaky, flaky_url = _start_replica(
        type("Flaky", (_SSEHandler,), {"delay": 0.002,
                                       "abort_after": 1}))
    dead_url = f"http://127.0.0.1:{_free_port()}"
    policy = PrefixAffinityPolicy()
    policy.set_ready_replicas([good_url, flaky_url, dead_url])
    # max_stream_resumes=0: this test is about slot accounting on the
    # FAILURE exit paths, so mid-stream aborts must stay aborted —
    # with the journal on, the LB would heal them on the good peer
    # (tests/test_stream_resume.py owns that path, including its own
    # slot-drain assertion).
    lb, target = _start_lb(policy, max_body_bytes=64 * 1024,
                           max_stream_resumes=0)
    spec = loadgen.LoadSpec(mix="chat", arrival="uniform", qps=40,
                            duration_s=1.0, seed=9, max_tokens=4)
    try:
        report = loadgen.run(target, spec, scrape_interval=0.5,
                             out_dir=str(tmp_path / "burst"))
        # An oversized body is refused with 413 before buffering; its
        # slot (never selected) must not corrupt the accounting.
        big = json.dumps({"prompt": [1] * 40000,
                          "max_tokens": 1}).encode()
        req = urllib.request.Request(target + "/generate", data=big,
                                     method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 413
        exc.value.read()
    finally:
        lb.shutdown()
        good.shutdown()
        flaky.shutdown()
    # Burst saw real failures (aborts from the flaky replica, retries
    # off the dead one) AND real successes.
    assert report["requests"]["ok"] > 0
    assert report["requests"]["error"] > 0
    # Mid-stream aborts surface as a truncated stream, however the
    # client's HTTP layer chose to report it.
    assert any(k in ("truncated_stream", "IncompleteRead")
               for k in report["requests"]["errors_by_kind"])
    # The whole point: every in-flight slot came back.
    with policy._lock:
        assert all(v == 0 for v in policy._inflight.values()), \
            policy._inflight


def test_prefix_affinity_bounded_load_spills_under_burst(
        tmp_state_dir, tmp_path):
    """Satellite: one dominant system prompt under an open-loop burst
    spills deterministically off its saturated ring owner instead of
    pinning the fleet's traffic on one replica — and the inflight
    counters still drain to zero."""
    hits = []
    handlers = [type(f"Slow{i}", (_SSEHandler,),
                     {"delay": 0.03, "hits": hits, "token_cap": 4})
                for i in range(3)]
    servers = [_start_replica(h) for h in handlers]
    urls = [url for _, url in servers]
    policy = PrefixAffinityPolicy()
    policy.set_ready_replicas(urls)
    lb, target = _start_lb(policy)
    # n_prefixes=1: every request hashes to the same ring owner.
    spec = loadgen.LoadSpec(mix="chat", arrival="uniform", qps=50,
                            duration_s=1.0, seed=6, n_prefixes=1,
                            max_tokens=4)
    try:
        report = loadgen.run(target, spec, scrape_interval=0.5,
                             out_dir=str(tmp_path / "spill"))
    finally:
        lb.shutdown()
        for server, _ in servers:
            server.shutdown()
    assert report["requests"]["ok"] == report["requests"]["scheduled"]
    # Bounded load: the owner took traffic, but so did >= 1 successor.
    assert len(set(hits)) >= 2, f"no spill: all hits on {set(hits)}"
    with policy._lock:
        assert all(v == 0 for v in policy._inflight.values())


# ================================================================ CLI
def test_cli_loadgen_run_and_report(tmp_state_dir):
    from click.testing import CliRunner

    from skypilot_tpu.cli import cli
    replica, url = _start_replica(
        type("Cli", (_SSEHandler,), {"delay": 0.001}))
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([url])
    lb, target = _start_lb(policy)
    runner = CliRunner()
    try:
        res = runner.invoke(cli, [
            "loadgen", "--target", target, "--qps", "10",
            "--duration", "1.0", "--seed", "5", "--slo-ttft", "1.0"])
        assert res.exit_code == 0, res.output
        assert "goodput" in res.output
        assert "sha256=" in res.output
        # report with no args renders the newest run.
        res2 = runner.invoke(cli, ["loadgen", "report"])
        assert res2.exit_code == 0, res2.output
        assert "goodput" in res2.output
        res3 = runner.invoke(cli, ["loadgen", "report", "--json"])
        assert res3.exit_code == 0
        assert json.loads(res3.output)["schedule_sha256"]
    finally:
        lb.shutdown()
        replica.shutdown()


def test_cli_loadgen_requires_target(tmp_state_dir):
    from click.testing import CliRunner

    from skypilot_tpu.cli import cli
    res = CliRunner().invoke(cli, ["loadgen"])
    assert res.exit_code != 0
    assert "--target" in res.output


def test_cli_loadgen_report_without_runs(tmp_state_dir):
    from click.testing import CliRunner

    from skypilot_tpu.cli import cli
    res = CliRunner().invoke(cli, ["loadgen", "report"])
    assert res.exit_code != 0
    assert "No recorded loadgen runs" in res.output


# =========================================== bench leg (real engine)
def test_measure_engine_slo_tiny_end_to_end(tmp_state_dir, monkeypatch):
    """The bench serving leg end to end on a tiny model: serve_llm
    replica + in-process LB + loadgen, returning the gated keys."""
    from skypilot_tpu.benchmark import decode_bench
    from skypilot_tpu.models import llama

    def tiny_build(family, **kw):
        return llama, llama.LlamaConfig.tiny(vocab_size=512)

    monkeypatch.setattr(decode_bench, "build", tiny_build)
    result = decode_bench.measure_engine_slo(
        "llama", slots=2, qps=4.0, duration_s=1.5, slo_ttft_s=30.0,
        slo_tpot_s=30.0, max_tokens=4)
    assert set(result) >= {"slo_goodput", "p99_ttft_s",
                           "loadgen_tok_s", "achieved_qps",
                           "offered_qps", "schedule_sha256"}
    assert result["errors"] == 0
    assert result["slo_goodput"] == 1.0
    assert result["p99_ttft_s"] > 0
    assert result["loadgen_tok_s"] > 0


# ========================================= schedule files + replay (sat.)
def test_schedule_save_load_roundtrip_and_tamper(tmp_path):
    """save_schedule → load_schedule is lossless (spec, requests, and
    float offsets at full precision → identical digest); a hand-edited
    file fails the pinned-digest check loudly."""
    spec = loadgen.LoadSpec(mix="chat", qps=18, duration_s=1.5, seed=13,
                            max_tokens=6)
    schedule = loadgen.build_schedule(spec)
    path = str(tmp_path / "schedule.json")
    digest = loadgen.save_schedule(path, spec, schedule)
    assert digest == loadgen.schedule_digest(schedule)
    spec2, schedule2, digest2 = loadgen.load_schedule(path)
    assert spec2 == spec
    assert schedule2 == schedule
    assert digest2 == digest
    # Tamper: change one prompt token — the recomputed digest no
    # longer matches the pinned one.
    doc = json.loads(pathlib.Path(path).read_text())
    doc["requests"][0]["prompt"][0] += 1
    pathlib.Path(path).write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="pinned digest"):
        loadgen.load_schedule(path)
    # Not-a-schedule fails before digest math.
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    with pytest.raises(ValueError, match="not a schedule"):
        loadgen.load_schedule(str(bad))


def test_run_schedule_file_replays_verbatim(tmp_state_dir, tmp_path):
    """`run(schedule_file=...)` replays a saved trace with NO spec in
    hand: the report records source="schedule" and pins the digest of
    what actually ran; spec-driven runs say source="spec"; neither
    input is an error."""
    replica, _ = _start_replica(
        type("Sched", (_SSEHandler,), {"delay": 0.001}))
    policy = RoundRobinPolicy()
    policy.set_ready_replicas(
        [f"http://127.0.0.1:{replica.server_address[1]}"])
    lb, target = _start_lb(policy)
    spec = loadgen.LoadSpec(mix="chat", qps=12, duration_s=1.0, seed=21,
                            max_tokens=4)
    schedule = loadgen.build_schedule(spec)
    path = str(tmp_path / "schedule.json")
    digest = loadgen.save_schedule(path, spec, schedule)
    try:
        report = loadgen.run(target, None, schedule_file=path,
                             scrape_interval=0.5,
                             out_dir=str(tmp_path / "replay"))
        spec_report = loadgen.run(target, spec, scrape_interval=0.5,
                                  out_dir=str(tmp_path / "fromspec"))
        with pytest.raises(ValueError, match="spec or a schedule"):
            loadgen.run(target, None)
    finally:
        lb.shutdown()
        replica.shutdown()
    assert report["source"] == "schedule"
    assert report["schedule_sha256"] == digest
    assert report["requests"]["scheduled"] == len(schedule)
    assert report["requests"]["error"] == 0
    assert spec_report["source"] == "spec"
    assert spec_report["schedule_sha256"] == digest   # same trace
    assert "source=schedule" in loadgen.format_report(report)
    # The replay leg re-persists the trace it ran, digest-stable.
    replay_doc = json.loads(
        (pathlib.Path(report["out_dir"]) / "schedule.json").read_text())
    assert replay_doc["digest"] == digest


def test_derive_spec_determinism_and_mix_detection():
    """derive_spec is order-insensitive and classifies the mix from
    the records alone: steady short prompts → chat, high inter-arrival
    CoV → bursty, long mean prompt → long_context. The chat cap is
    moment-matched: a schedule built from the derived spec reproduces
    the observed mean prompt length."""
    def rec(i, ts, plen, prefix="aa" * 8):
        return {"request_id": f"{i:04x}" * 8, "ts": ts,
                "path": "/generate", "prompt_tokens": plen,
                "max_tokens": 8, "temperature": 0.0,
                "prefix_hash": prefix, "status": "200"}

    # Steady arrivals, mean plen 82, two prefixes.
    chat = [rec(i, 100.0 + i * 0.1, 68 + (i % 2) * 28,
                prefix=("aa" * 8 if i % 2 else "bb" * 8))
            for i in range(40)]
    d1 = loadgen.derive_spec(chat)
    d2 = loadgen.derive_spec(list(reversed(chat)))
    assert d1 == d2
    assert loadgen.schedule_digest(loadgen.build_schedule(d1)) == \
        loadgen.schedule_digest(loadgen.build_schedule(d2))
    assert d1.mix == "chat"
    assert d1.n_prefixes == 2
    assert d1.max_tokens == 8
    sched = loadgen.build_schedule(d1)
    observed_mean = sum(r["prompt_tokens"] for r in chat) / len(chat)
    derived_mean = sum(len(r.prompt) for r in sched) / len(sched)
    assert abs(derived_mean - observed_mean) <= 8, \
        (observed_mean, derived_mean)
    # Different records → different content-derived seed → digest.
    other = loadgen.derive_spec(chat[:30])
    assert other.seed != d1.seed

    # Bursty: tight clumps separated by long gaps → CoV >> 1.
    ts = []
    for clump in range(8):
        ts.extend(clump * 3.0 + k * 0.01 for k in range(5))
    bursty = [rec(i, 100.0 + t, 80) for i, t in enumerate(ts)]
    assert loadgen.derive_spec(bursty).mix == "bursty"

    # Long-context: mean prompt length over the 320-token knee.
    lctx = [rec(i, 100.0 + i * 0.1, 600) for i in range(20)]
    d = loadgen.derive_spec(lctx)
    assert d.mix == "long_context"
    assert d.long_prompt_tokens == 600

    # No usable records is a loud error, not an empty spec.
    with pytest.raises(ValueError, match="no /generate records"):
        loadgen.derive_spec([{"path": "/metrics", "ts": 1.0}])


def test_report_driver_lag_and_saturation_warning(tmp_path):
    """Open-loop integrity: the report carries dispatch-lag
    percentiles, and a lag p99 above one scrape interval raises the
    driver-saturation WARNING (rendered by format_report)."""
    spec = loadgen.LoadSpec(mix="chat", qps=5, duration_s=1.0, seed=2)
    schedule = loadgen.build_schedule(spec)
    digest = loadgen.schedule_digest(schedule)
    scraper = loadgen.MetricsScraper("http://127.0.0.1:1",  # never run
                                     1.0, tmp_path / "m.jsonl")

    def results(lag):
        return [{"index": r.index, "ok": True, "code": 200,
                 "error": None, "ttft_s": 0.01, "tpot_s": 0.005,
                 "e2e_s": 0.05, "tokens": 4,
                 "sent_offset": r.at + lag, "dispatch_lag_s": lag}
                for r in schedule]

    healthy = loadgen._build_report(
        spec, schedule, digest, results(0.002), 1.5, scraper, "t",
        dispatch_window=1.0, slo_ttft_s=None, slo_tpot_s=None,
        faults=None, faults_at=0.0, scrape_interval=1.0)
    assert healthy["driver"]["lag_p99_s"] == pytest.approx(0.002)
    assert healthy["driver"]["lag_s"]["p50"] is not None
    assert healthy["driver"]["warning"] is None
    assert "WARNING" not in loadgen.format_report(healthy)

    saturated = loadgen._build_report(
        spec, schedule, digest, results(2.5), 4.0, scraper, "t",
        dispatch_window=3.5, slo_ttft_s=None, slo_tpot_s=None,
        faults=None, faults_at=0.0, scrape_interval=1.0)
    assert saturated["driver"]["warning"] is not None
    assert "under-driving" in saturated["driver"]["warning"]
    rendered = loadgen.format_report(saturated)
    assert "WARNING" in rendered and "driver saturated" in rendered


def test_cli_loadgen_schedule_flag(tmp_state_dir, tmp_path):
    """`stpu loadgen --schedule FILE` replays a saved trace without
    any workload flags; the rendered report says so."""
    from click.testing import CliRunner

    from skypilot_tpu.cli import cli
    replica, url = _start_replica(
        type("CliSched", (_SSEHandler,), {"delay": 0.001}))
    policy = RoundRobinPolicy()
    policy.set_ready_replicas([url])
    lb, target = _start_lb(policy)
    spec = loadgen.LoadSpec(mix="chat", qps=10, duration_s=1.0, seed=5,
                            max_tokens=4)
    path = str(tmp_path / "schedule.json")
    digest = loadgen.save_schedule(path, spec,
                                   loadgen.build_schedule(spec))
    runner = CliRunner()
    try:
        res = runner.invoke(cli, ["loadgen", "--target", target,
                                  "--schedule", path])
        assert res.exit_code == 0, res.output
        assert "source=schedule" in res.output
        assert f"sha256={digest[:12]}" in res.output
    finally:
        lb.shutdown()
        replica.shutdown()
