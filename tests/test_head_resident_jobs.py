"""Head-resident job queue: the VERDICT r2 #1 done-criterion.

The job DB, job logs, and the detached gang driver live on the cluster
HEAD (reference: _exec_code_on_head + JobLibCodeGen,
sky/backends/cloud_vm_ray_backend.py:3180, sky/skylet/job_lib.py:803).
Proven here for a plain (non-controller) cluster:

  * the client process is hard-killed right after submit — the job still
    runs to completion;
  * `queue` from a DIFFERENT client process reads the head's state;
  * the on-host daemon observes idleness from the head DB and autostops
    the cluster with no client anywhere.

Plus unit coverage of the head-side transports: the SSH-cluster job spec
(head runs rank 0 as a plain subprocess, reaches workers over internal
IPs with the cluster-internal key) and gang_exec's "exec" host kind.
"""
import json
import os
import pathlib
import subprocess
import sys
import textwrap
import time

import pytest

from skypilot_tpu import core
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.agent import gang_exec
from skypilot_tpu.agent import job_lib
from skypilot_tpu.backends import slice_backend
from skypilot_tpu.provision.common import ClusterInfo, InstanceInfo
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task


def _wait(pred, timeout=30, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ------------------------------------------------------- the done-criterion
def test_job_survives_client_death_and_daemon_autostops(
        tmp_state_dir, monkeypatch):
    """Client submits and is KILLED; job completes; a fresh client's
    `queue` reads head state; the daemon autostops the idle cluster."""
    monkeypatch.setenv("STPU_DISABLE_DAEMON", "0")
    monkeypatch.setenv("STPU_DAEMON_INTERVAL", "0.2")

    # The "client": a separate process that launches with autostop -i 0,
    # a job that takes ~1.5s, then hard-exits without waiting.
    client_script = textwrap.dedent("""
        import os
        from skypilot_tpu import execution
        from skypilot_tpu.resources import Resources
        from skypilot_tpu.task import Task
        task = Task("survivor",
                    run="sleep 1.5 && echo finished > $HOME/marker.txt")
        task.set_resources(Resources(cloud="local"))
        job_id, handle = execution.launch(
            task, cluster_name="t-headres", detach_run=True,
            stream_logs=False, idle_minutes_to_autostop=0)
        print(f"JOBID={job_id} HEAD={handle.head_home}", flush=True)
        os._exit(0)  # hard death: no cleanup, no atexit, no waiting
    """)
    proc = subprocess.run([sys.executable, "-c", client_script],
                          capture_output=True, text=True, timeout=120,
                          env=dict(os.environ))
    assert proc.returncode == 0, proc.stderr[-3000:]
    fields = dict(kv.split("=", 1)
                  for kv in proc.stdout.split() if "=" in kv)
    job_id = int(fields["JOBID"])
    head_home = pathlib.Path(fields["HEAD"])

    # The job was submitted while the client lived; it finishes AFTER
    # the client died (the sleep outlives the client by construction).
    marker = head_home / "marker.txt"
    assert _wait(marker.exists, timeout=30), \
        "job did not run to completion after client death"

    # A brand-new client process reads the job from the HEAD's DB.
    out = subprocess.run(
        [sys.executable, "-c",
         "from skypilot_tpu import core; import json; "
         "print(json.dumps(core.queue('t-headres')))"],
        capture_output=True, text=True, timeout=60, env=dict(os.environ))
    assert out.returncode == 0, out.stderr[-3000:]
    jobs = json.loads(out.stdout.strip().splitlines()[-1])
    by_id = {j["job_id"]: j for j in jobs}
    assert _wait(lambda: core.job_status(
        "t-headres", [job_id])[job_id] == "SUCCEEDED", timeout=20)
    assert by_id[job_id]["job_name"] == "survivor"

    # With zero clients involved, the daemon sees the idle head DB and
    # stops the cluster via the provider API.
    from skypilot_tpu.provision import local as local_provider

    def provider_stopped():
        statuses = local_provider.query_instances("t-headres", {})
        return statuses and set(statuses.values()) == {"stopped"}
    assert _wait(provider_stopped, timeout=30), \
        "daemon never autostopped the idle cluster"
    # Terminate so the host dir (and any daemon still finishing its
    # last tick) is gone before the next test; the conftest reaper is
    # the backstop, not the plan.
    from skypilot_tpu import core as core_lib
    core_lib.down("t-headres", purge=True)


# ------------------------------------------------ head-side spec transports
def _ssh_handle(n_hosts=3):
    instances = {
        f"w{i}": InstanceInfo(
            instance_id=f"w{i}", internal_ip=f"10.0.0.{i}",
            external_ip=f"34.1.2.{i}", slice_id="s0", host_index=i,
            tags={})
        for i in range(n_hosts)
    }
    info = ClusterInfo(
        cluster_name="ssh-c", provider_name="gcp",
        region="us-central1", zone="us-central1-a",
        instances=instances, head_instance_id="w0",
        ssh_user="stpu", ssh_key_path="~/.ssh/id_rsa",
        provider_config={"ssh_proxy_command": "corp-proxy %h"})
    res = Resources(cloud="gcp", accelerator="tpu-v5p-32")
    return slice_backend.SliceHandle("ssh-c", res, 1, info)


def test_ssh_cluster_spec_is_head_relative(tmp_state_dir):
    """Rank 0 = plain subprocess on the head; workers = INTERNAL ips +
    the cluster-internal key; never the client's key or proxy."""
    handle = _ssh_handle(3)
    task = Task("spec", run="echo hi")
    task.set_resources(handle.launched_resources)
    backend = slice_backend.SliceBackend()
    spec = backend._build_job_spec(handle, task, "2026-01-01-00-00-00")

    assert "job_id" not in spec  # assigned on the head by job_cli
    assert spec["hosts"][0]["kind"] == "exec"
    for rank, host in enumerate(spec["hosts"][1:], start=1):
        assert host["kind"] == "ssh"
        assert host["ip"] == f"10.0.0.{rank}"  # internal, not 34.x
        assert host["ssh_key_path"] == agent_constants.INTERNAL_KEY_PATH
        assert host["proxy_command"] is None  # slice-internal network
    assert spec["node_ips"] == ["10.0.0.0", "10.0.0.1", "10.0.0.2"]


def test_gang_exec_kind_exec_runs_on_head(tmp_state_dir, tmp_path,
                                          monkeypatch):
    """The "exec" host kind runs the command as the head's own process
    (no SSH-to-self), with the rank env contract intact."""
    head = tmp_path / "headhome"
    head.mkdir()
    monkeypatch.setenv("HOME", str(head))
    job_id = job_lib.add_job("t", "u", "ts", "")
    spec = {
        "job_id": job_id,
        "task_id": "t-1",
        "cluster_name": "c",
        "node_ips": ["10.0.0.0"],
        "num_slices": 1,
        "hosts_per_slice": 1,
        "chips_per_host": 0,
        "envs": {},
        "run_cmd": "echo rank=$SKYPILOT_NODE_RANK > out.txt",
        "log_dir": str(head / "logs"),
        "hosts": [{"kind": "exec", "slice_index": 0}],
        "agent_home": None,
    }
    rc = gang_exec.run_gang(spec)
    assert rc == 0
    assert (head / "out.txt").read_text().strip() == "rank=0"
    assert job_lib.get_job(job_id)["status"] == "SUCCEEDED"


# ---------------------------------------------------------- job_cli seam
def test_job_cli_round_trip(tmp_state_dir, tmp_path, monkeypatch):
    """submit/queue/status/cancel through the CLI seam the client uses."""
    from skypilot_tpu.agent import job_cli

    head = tmp_path / "head2"
    head.mkdir()
    monkeypatch.setenv("HOME", str(head))

    spec = {
        "job_name": "cli-job", "username": "tester",
        "run_timestamp": "ts", "cluster_name": "c",
        "node_ips": ["10.0.0.0"], "num_slices": 1,
        "hosts_per_slice": 1, "chips_per_host": 0, "envs": {},
        "run_cmd": "sleep 30",
        "hosts": [{"kind": "exec", "slice_index": 0}],
    }
    spec_path = tmp_path / "spec.json"
    spec_path.write_text(json.dumps(spec))

    def rpc(args):
        proc = subprocess.run(
            [sys.executable, "-m", "skypilot_tpu.agent.job_cli"] + args,
            capture_output=True, text=True, timeout=60,
            env=dict(os.environ))
        assert proc.returncode == 0, proc.stderr[-2000:]
        return job_cli.parse_reply(proc.stdout)

    reply = rpc(["submit", str(spec_path)])
    jid = reply["job_id"]
    assert jid == 1
    # Spec rewritten in place with head-assigned fields.
    final = json.loads(spec_path.read_text())
    assert final["job_id"] == jid
    assert final["agent_home"] is None

    assert _wait(lambda: rpc(["status", str(jid)])["status"] == "RUNNING")
    jobs = rpc(["queue"])
    assert jobs[0]["job_name"] == "cli-job"
    assert jobs[0]["log_dir"].endswith(f"job-{jid}")

    cancelled = rpc(["cancel", "--jobs", str(jid)])
    assert cancelled == [jid]
    assert _wait(
        lambda: rpc(["status", str(jid)])["status"] == "CANCELLED")


def test_cancel_empty_list_cancels_nothing(tmp_state_dir, monkeypatch):
    """backend.cancel_jobs(handle, []) must be a no-op, not cancel-all
    (an empty --jobs value would read as 'all live jobs' in job_cli)."""
    backend = slice_backend.SliceBackend()
    called = []
    monkeypatch.setattr(backend, "_job_rpc",
                        lambda *a, **k: called.append(a) or [])
    assert backend.cancel_jobs(object(), []) == []
    assert called == []  # never reached the head


def test_parse_reply_ignores_login_shell_noise():
    from skypilot_tpu.agent import job_cli
    noisy = ("Welcome to Ubuntu\nmotd chatter\n"
             'STPU_RPC:{"job_id": 7}\n')
    assert job_cli.parse_reply(noisy) == {"job_id": 7}
    with pytest.raises(ValueError, match="no STPU_RPC"):
        job_cli.parse_reply("just noise\n")


# ------------------------------------------------- failover ergonomics (r2 #8)
def test_retry_backoff_schedule():
    """Exponential with +-20% jitter, capped at 5 minutes — never the r2
    5-second hot loop."""
    from skypilot_tpu.backends.slice_backend import _retry_backoff_seconds
    for rnd, nominal in [(0, 10), (1, 20), (3, 80), (10, 300)]:
        vals = [_retry_backoff_seconds(rnd) for _ in range(20)]
        assert all(nominal * 0.8 <= v <= nominal * 1.2 for v in vals), \
            (rnd, min(vals), max(vals))
    assert len({round(v, 6) for v in
                [_retry_backoff_seconds(2) for _ in range(10)]}) > 1


def test_ssh_env_not_in_argv():
    """User env (secrets!) must ride stdin, never the ssh argv that any
    user on a shared host can read via ps."""
    host = {"kind": "ssh", "ip": "10.0.0.1", "ssh_user": "stpu",
            "ssh_key_path": "~/.ssh/stpu_internal_key", "ssh_port": 22,
            "proxy_command": None}
    env = {"HF_TOKEN": "hf_secret_value", "SKYPILOT_NODE_RANK": "1"}
    argv, script = gang_exec._ssh_argv_and_script(
        host, "python train.py", env, coord_port=9123)
    joined = " ".join(argv)
    assert "hf_secret_value" not in joined
    assert "python train.py" not in joined  # command rides stdin too
    assert "export HF_TOKEN=hf_secret_value" in script
    assert "python train.py" in script
    # Wrapper + tunnel still wired.
    assert "-R" in argv
    assert "host_wrapper" in script
    assert "STPU_GANG_COORD_ADDR" in script
