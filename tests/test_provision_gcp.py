"""Hermetic tests for the GCP TPU provisioner.

All HTTP is intercepted at ``gcp.rest`` by an in-memory fake of the Cloud
TPU v2 API (nodes + queuedResources), so these cover the full SPI —
create / wait / query / stop-refusal / terminate / preemption / failover
error parsing — with zero credentials, the way the reference's dryrun
harness fakes all clouds (tests/common.py:11 enable_all_clouds).
"""
from __future__ import annotations

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import gcp

ZONE = "us-east5-a"
PARENT = f"projects/testproj/locations/{ZONE}"


class FakeTpuService:
    """In-memory twin of tpu.googleapis.com/v2 nodes + queuedResources."""

    def __init__(self):
        self.nodes = {}            # node_id -> node dict
        self.queued = {}           # qr_id -> qr dict
        self.calls = []            # (method, path)
        self.create_error = None   # (status, body) to inject on create
        self.hosts_per_node = 1    # networkEndpoints fan-out

    # -- helpers -------------------------------------------------------
    def _endpoints(self, n):
        return [{"ipAddress": f"10.0.0.{i+1}",
                 "accessConfig": {"externalIp": f"34.0.0.{i+1}"}}
                for i in range(n)]

    def make_ready(self, node_id=None):
        for nid, node in self.nodes.items():
            if node_id in (None, nid):
                node["state"] = "READY"
        for qr in self.queued.values():
            qr["state"] = {"state": "ACTIVE"}

    def preempt(self, node_id=None):
        for nid, node in self.nodes.items():
            if node_id in (None, nid):
                node["state"] = "PREEMPTED"

    # -- the rest() twin ----------------------------------------------
    def __call__(self, method, path, body=None, params=None):
        self.calls.append((method, path))
        params = params or {}
        if method == "POST" and path.endswith("/nodes"):
            if self.create_error:
                raise gcp.GcpApiError(*self.create_error,
                                      context="create node")
            nid = params["nodeId"]
            self.nodes[nid] = dict(
                body, name=f"{PARENT}/nodes/{nid}", state="CREATING",
                networkEndpoints=self._endpoints(self.hosts_per_node))
            return {"name": f"{PARENT}/operations/op-{nid}"}
        if method == "POST" and path.endswith("/queuedResources"):
            if self.create_error:
                raise gcp.GcpApiError(*self.create_error,
                                      context="create qr")
            qid = params["queuedResourceId"]
            spec = body["tpu"]["nodeSpec"][0]
            nid = spec["nodeId"]
            self.queued[qid] = {
                "name": f"{PARENT}/queuedResources/{qid}",
                "state": {"state": "PROVISIONING"}}
            self.nodes[nid] = dict(
                spec["node"], name=f"{PARENT}/nodes/{nid}",
                state="CREATING",
                networkEndpoints=self._endpoints(self.hosts_per_node))
            return {"name": f"{PARENT}/operations/op-{qid}"}
        if method == "GET" and path.endswith("/nodes"):
            return {"nodes": list(self.nodes.values())}
        if method == "GET" and path.endswith("/queuedResources"):
            return {"queuedResources": list(self.queued.values())}
        if method == "POST" and path.endswith(":start"):
            nid = path.rsplit("/", 1)[-1].split(":")[0]
            self.nodes[nid]["state"] = "READY"
            return {}
        if method == "POST" and path.endswith(":stop"):
            nid = path.rsplit("/", 1)[-1].split(":")[0]
            self.nodes[nid]["state"] = "STOPPED"
            return {}
        if method == "DELETE" and "/nodes/" in path:
            nid = path.rsplit("/", 1)[-1]
            if nid not in self.nodes:
                raise gcp.GcpApiError(404, {"error": {
                    "status": "NOT_FOUND", "message": "no node"}})
            del self.nodes[nid]
            return {}
        if method == "DELETE" and "/queuedResources/" in path:
            qid = path.rsplit("/", 1)[-1]
            self.queued.pop(qid, None)
            return {}
        raise AssertionError(f"unexpected call {method} {path}")


@pytest.fixture()
def fake(monkeypatch):
    svc = FakeTpuService()
    monkeypatch.setattr(gcp, "rest", svc)
    monkeypatch.setattr(gcp, "_gcloud_project", lambda: "testproj")
    return svc


def _config(accelerator="tpu-v5e-8", hosts_per_slice=1, num_slices=1,
            **kw):
    base = dict(accelerator=accelerator, hosts_per_slice=hosts_per_slice,
                num_slices=num_slices,
                runtime_version="v2-alpha-tpuv5-lite",
                use_spot=False, project_id="testproj", zone=ZONE)
    base.update(kw)
    return base


# ---------------------------------------------------------------- create
def test_create_single_host_uses_node_api(fake):
    rec = gcp.run_instances("us-east5", ZONE, "c1", _config())
    assert rec.created_instance_ids == ["c1-s0"]
    assert ("POST", f"{PARENT}/nodes") in fake.calls
    assert not any("queuedResources" in p for _, p in fake.calls)
    node = fake.nodes["c1-s0"]
    assert node["acceleratorType"] == "v5litepod-8"
    assert node["labels"]["stpu-cluster"] == "c1"


def test_create_pod_uses_queued_resources(fake):
    fake.hosts_per_node = 4
    rec = gcp.run_instances(
        "us-east5", ZONE, "c1",
        _config(accelerator="tpu-v5e-16", hosts_per_slice=4))
    assert rec.created_instance_ids == ["c1-s0"]
    assert ("POST", f"{PARENT}/queuedResources") in fake.calls
    assert "c1-s0" in fake.queued


def test_multislice_creates_one_node_per_slice(fake):
    gcp.run_instances("us-east5", ZONE, "c1", _config(num_slices=3))
    assert set(fake.nodes) == {"c1-s0", "c1-s1", "c1-s2"}


def test_accelerator_type_translation():
    assert gcp._gcp_accelerator_type("tpu-v4-8") == "v4-16"
    assert gcp._gcp_accelerator_type("tpu-v5e-16") == "v5litepod-16"
    assert gcp._gcp_accelerator_type("tpu-v5p-64") == "v5p-64"
    assert gcp._gcp_accelerator_type("tpu-v6e-8") == "v6e-8"


def test_spot_sets_scheduling_config(fake):
    gcp.run_instances("us-east5", ZONE, "c1", _config(use_spot=True))
    assert fake.nodes["c1-s0"]["schedulingConfig"] == {
        "preemptible": True}


# ------------------------------------------------------------------ wait
def test_wait_returns_when_ready(fake, monkeypatch):
    monkeypatch.setattr(gcp, "_POLL_INTERVAL_SECONDS", 0)
    gcp.run_instances("us-east5", ZONE, "c1", _config(zone=ZONE))
    fake.make_ready()
    gcp.wait_instances("us-east5", "c1", "running",
                       {"zone": ZONE, "project_id": "testproj"})  # no raise


def test_wait_raises_blocklist_on_failed_queued_resource(fake,
                                                         monkeypatch):
    monkeypatch.setattr(gcp, "_POLL_INTERVAL_SECONDS", 0)
    fake.hosts_per_node = 4
    gcp.run_instances("us-east5", ZONE, "c1",
                      _config(accelerator="tpu-v5e-16", hosts_per_slice=4))
    fake.queued["c1-s0"]["state"] = {"state": "FAILED"}
    with pytest.raises(exceptions.ProvisionError) as exc:
        gcp.wait_instances("us-east5", "c1", "running",
                           {"zone": ZONE, "project_id": "testproj"})
    assert exc.value.blocklist_zone == ZONE


# ----------------------------------------------------------------- query
def test_query_maps_states_per_host(fake):
    fake.hosts_per_node = 2
    gcp.run_instances("us-east5", ZONE, "c1",
                      _config(accelerator="tpu-v5e-8", hosts_per_slice=2))
    fake.make_ready()
    statuses = gcp.query_instances("c1", _config())
    assert statuses == {"c1-s0-w0": "running", "c1-s0-w1": "running"}
    fake.preempt()
    statuses = gcp.query_instances("c1", _config())
    assert set(statuses.values()) == {"preempted"}


def test_query_ignores_other_clusters(fake):
    gcp.run_instances("us-east5", ZONE, "c1", _config())
    gcp.run_instances("us-east5", ZONE, "c2", _config())
    assert set(gcp.query_instances("c1", _config())) == {"c1-s0-w0"}


# ---------------------------------------------------------- cluster info
def test_get_cluster_info_rank_order(fake):
    fake.hosts_per_node = 4
    gcp.run_instances("us-east5", ZONE, "c1",
                      _config(accelerator="tpu-v5e-16", hosts_per_slice=4))
    fake.make_ready()
    info = gcp.get_cluster_info("us-east5", "c1", _config())
    insts = info.ordered_instances()
    assert [i.instance_id for i in insts] == [
        f"c1-s0-w{i}" for i in range(4)]
    assert [i.internal_ip for i in insts] == [
        f"10.0.0.{i+1}" for i in range(4)]
    assert insts[0].external_ip == "34.0.0.1"
    assert info.head_instance_id == "c1-s0-w0"


# ---------------------------------------------------------- stop / down
def test_stop_single_host(fake):
    gcp.run_instances("us-east5", ZONE, "c1", _config())
    fake.make_ready()
    gcp.stop_instances("c1", _config())
    assert fake.nodes["c1-s0"]["state"] == "STOPPED"


def test_stop_refused_for_pod(fake):
    fake.hosts_per_node = 4
    gcp.run_instances("us-east5", ZONE, "c1",
                      _config(accelerator="tpu-v5e-16", hosts_per_slice=4))
    fake.make_ready()
    with pytest.raises(exceptions.NotSupportedError):
        gcp.stop_instances("c1", _config())


def test_resume_stopped_node_calls_start(fake):
    gcp.run_instances("us-east5", ZONE, "c1", _config())
    fake.nodes["c1-s0"]["state"] = "STOPPED"
    rec = gcp.run_instances("us-east5", ZONE, "c1", _config())
    assert rec.resumed_instance_ids == ["c1-s0"]
    assert fake.nodes["c1-s0"]["state"] == "READY"


def test_rerun_is_idempotent_while_ready(fake):
    gcp.run_instances("us-east5", ZONE, "c1", _config())
    fake.make_ready()
    rec = gcp.run_instances("us-east5", ZONE, "c1", _config())
    assert rec.created_instance_ids == []
    assert rec.resumed_instance_ids == ["c1-s0"]


def test_preempted_husk_recreated(fake):
    """Spot slice preempted → husk deleted and a fresh slice created
    (reference: need_cleanup_after_preemption, sky/resources.py:595)."""
    gcp.run_instances("us-east5", ZONE, "c1", _config(use_spot=True))
    fake.preempt()
    rec = gcp.run_instances("us-east5", ZONE, "c1", _config(use_spot=True))
    assert rec.created_instance_ids == ["c1-s0"]
    assert fake.nodes["c1-s0"]["state"] == "CREATING"


def test_terminate_deletes_nodes_and_queued(fake):
    fake.hosts_per_node = 4
    gcp.run_instances("us-east5", ZONE, "c1",
                      _config(accelerator="tpu-v5e-16", hosts_per_slice=4))
    gcp.terminate_instances("c1", _config())
    assert fake.nodes == {}
    assert fake.queued == {}
    assert gcp.query_instances("c1", _config()) == {}


# -------------------------------------------------------- error parsing
def _err(status, code, message):
    return (status, {"error": {"status": code, "code": code,
                               "message": message}})


def test_stockout_blocklists_zone(fake):
    fake.create_error = _err(
        429, "RESOURCE_EXHAUSTED",
        f'There is no more capacity in the zone "{ZONE}"')
    with pytest.raises(exceptions.ProvisionError) as exc:
        gcp.run_instances("us-east5", ZONE, "c1", _config())
    assert exc.value.blocklist_zone == ZONE
    assert exc.value.blocklist_region is None


def test_region_quota_blocklists_region(fake):
    fake.create_error = _err(
        429, "RESOURCE_EXHAUSTED",
        "Quota 'TPUV5sPodPerProjectPerRegionForTPUAPI' exhausted. "
        "Limit 32 in region us-east5")
    with pytest.raises(exceptions.ProvisionError) as exc:
        gcp.run_instances("us-east5", ZONE, "c1", _config())
    assert exc.value.blocklist_region == "us-east5"


def test_preempted_during_creation_blocklists_zone(fake):
    fake.create_error = (400, {"error": {
        "code": 3,
        "message": "update is not supported while in state PREEMPTED"}})
    with pytest.raises(exceptions.ProvisionError) as exc:
        gcp.run_instances("us-east5", ZONE, "c1", _config())
    assert exc.value.blocklist_zone == ZONE


def test_permission_denied_raises_no_access(fake):
    fake.create_error = _err(403, "PERMISSION_DENIED",
                             "Cloud TPU API has not been used")
    with pytest.raises(exceptions.NoCloudAccessError):
        gcp.run_instances("us-east5", ZONE, "c1", _config())


def test_terminate_surfaces_auth_failure(fake, monkeypatch):
    """A 403 while tearing down must NOT read as 'nothing to delete' —
    the slices would keep billing behind a removed cluster record."""
    gcp.run_instances("us-east5", ZONE, "c1", _config())

    def deny(method, path, body=None, params=None):
        if method == "GET" and path.endswith("/nodes"):
            raise gcp.GcpApiError(403, {"error": {
                "status": "PERMISSION_DENIED", "message": "denied"}})
        return fake(method, path, body=body, params=params)
    monkeypatch.setattr(gcp, "rest", deny)
    with pytest.raises(exceptions.NoCloudAccessError):
        gcp.terminate_instances("c1", _config())
    # Status queries stay lenient: unauthorized region reads as absent.
    assert gcp.query_instances("c1", _config()) == {}


def test_transient_error_retryable_in_zone(fake):
    fake.create_error = _err(503, "UNAVAILABLE", "backend unavailable")
    with pytest.raises(exceptions.ProvisionError) as exc:
        gcp.run_instances("us-east5", ZONE, "c1", _config())
    assert exc.value.retryable_in_zone
    assert exc.value.blocklist_zone is None


# ---------------------------------------------------------------- ports
class FakeComputeService:
    """In-memory twin of compute.googleapis.com firewalls + operations."""

    def __init__(self):
        self.firewalls = {}   # name -> rule dict
        self.calls = []       # (method, path)
        self._op_n = 0

    def _op(self):
        self._op_n += 1
        return {"name": f"op-{self._op_n}", "status": "DONE"}

    def __call__(self, method, path, body=None, params=None):
        self.calls.append((method, path))
        if "/global/firewalls" in path:
            name = path.rsplit("/", 1)[-1]
            if method == "GET":
                if name not in self.firewalls:
                    raise gcp.GcpApiError(404, {"error": {
                        "status": "NOT_FOUND", "message": "no rule"}})
                return dict(self.firewalls[name])
            if method == "POST":
                self.firewalls[body["name"]] = dict(body)
                return self._op()
            if method == "PATCH":
                self.firewalls[name].update(body)
                return self._op()
            if method == "DELETE":
                if name not in self.firewalls:
                    raise gcp.GcpApiError(404, {"error": {
                        "status": "NOT_FOUND", "message": "no rule"}})
                del self.firewalls[name]
                return self._op()
        if "/global/operations/" in path:
            return {"name": path.rsplit("/", 1)[-1], "status": "DONE"}
        raise AssertionError(f"unexpected compute call {method} {path}")


@pytest.fixture()
def fake_compute(monkeypatch):
    svc = FakeComputeService()
    monkeypatch.setattr(gcp, "compute_rest", svc)
    monkeypatch.setattr(gcp, "_gcloud_project", lambda: "testproj")
    return svc


def test_open_ports_creates_tagged_rule(fake_compute):
    gcp.open_ports("c1", ["8080", "30000-30100"], _config())
    rule = fake_compute.firewalls[gcp._firewall_rule_name("c1")]
    assert rule["direction"] == "INGRESS"
    assert rule["targetTags"] == [gcp._network_tag("c1")]
    assert rule["allowed"] == [
        {"IPProtocol": "tcp", "ports": ["30000-30100", "8080"]}]
    assert rule["network"].endswith("/global/networks/default")


def test_open_ports_idempotent_and_merging(fake_compute):
    gcp.open_ports("c1", ["8080"], _config())
    calls_after_create = len(fake_compute.calls)
    # Same ports again: GET only, no PATCH.
    gcp.open_ports("c1", ["8080"], _config())
    assert len(fake_compute.calls) == calls_after_create + 1
    # New port merges instead of clobbering (serve LB range must survive
    # a later launch-with-ports against the same cluster).
    gcp.open_ports("c1", ["9090"], _config())
    rule = fake_compute.firewalls[gcp._firewall_rule_name("c1")]
    assert rule["allowed"][0]["ports"] == ["8080", "9090"]


def test_cleanup_ports_deletes_rule_and_tolerates_absent(fake_compute):
    gcp.open_ports("c1", ["8080"], _config())
    gcp.cleanup_ports("c1", ["8080"], _config())
    assert not fake_compute.firewalls
    gcp.cleanup_ports("c1", ["8080"], _config())  # 404 swallowed


def test_node_body_carries_network_tag(fake):
    gcp.run_instances("us-east5", ZONE, "c1", _config())
    # Cluster tag (open_ports scoping) + shared stpu tag (bootstrap
    # ssh/internal rule scoping on shared VPCs).
    assert fake.nodes["c1-s0"]["tags"] == [gcp._network_tag("c1"),
                                           gcp._COMMON_TAG]


def test_cleanup_ports_also_deletes_legacy_rule_name(fake_compute):
    """A cluster provisioned before the hash-suffixed tag format still
    tears down its (legacy-named) ingress rule — cleanup must not leak
    open firewall rules across the format change."""
    legacy = gcp._legacy_network_tag("old.cluster") + "-ports"
    fake_compute.firewalls[legacy] = {"name": legacy}
    gcp.cleanup_ports("old.cluster", ["8080"], _config())
    assert legacy not in fake_compute.firewalls
    # Both names absent: still a clean no-op.
    gcp.cleanup_ports("old.cluster", ["8080"], _config())


def test_network_tag_collision_resistant():
    """Sanitize/truncate is lossy: names that sanitize ('a.b' vs 'a-b')
    or truncate (long shared prefixes) identically must still get
    DISTINCT tags, or two clusters alias one firewall rule and tearing
    down either deletes the other's ingress (ADVICE round 5). The raw-
    name hash suffix restores injectivity, within RFC1035 limits."""
    import re
    assert gcp._network_tag("a.b") != gcp._network_tag("a-b")
    long_a = "cluster-" + "x" * 80 + "-a"
    long_b = "cluster-" + "x" * 80 + "-b"
    assert gcp._network_tag(long_a) != gcp._network_tag(long_b)
    # Case is folded by sanitization, so it too needs the hash.
    assert gcp._network_tag("Train") != gcp._network_tag("train")
    for name in ("a.b", "a-b", long_a, "Train", "c1"):
        tag = gcp._network_tag(name)
        assert re.fullmatch(r"[a-z][a-z0-9-]*[a-z0-9]", tag)
        assert len(tag) <= 63
        assert len(gcp._firewall_rule_name(name)) <= 63
        assert gcp._network_tag(name) == tag  # deterministic


def test_invalid_port_spec_rejected(fake_compute):
    with pytest.raises(exceptions.ProvisionError):
        gcp.open_ports("c1", ["not-a-port"], _config())


# ------------------------------------------------------------- bootstrap
class FakeComputeWithNetworks(FakeComputeService):
    def __init__(self, networks=("default",)):
        super().__init__()
        self.networks = set(networks)

    def __call__(self, method, path, body=None, params=None):
        if "/global/networks/" in path and method == "GET":
            self.calls.append((method, path))
            name = path.rsplit("/", 1)[-1]
            if name not in self.networks:
                raise gcp.GcpApiError(404, {"error": {
                    "status": "NOT_FOUND", "message": "no network"}})
            return {"name": name}
        return super().__call__(method, path, body=body, params=params)


def test_bootstrap_creates_ssh_and_internal_rules(monkeypatch):
    """bootstrap_instances ensures ssh + intra-VPC ingress exist before
    any instance waits on them (reference:
    sky/provision/gcp/config.py:392-540, constants.py:57-84)."""
    svc = FakeComputeWithNetworks()
    monkeypatch.setattr(gcp, "compute_rest", svc)
    monkeypatch.setattr(gcp, "_gcloud_project", lambda: "testproj")
    gcp.bootstrap_instances("us-east5", "c1", _config())
    names = set(svc.firewalls)
    assert any(n.endswith("allow-ssh") for n in names)
    assert any(n.endswith("allow-internal") for n in names)
    assert "stpu-default-allow-ssh" in names  # no double prefix
    ssh_rule = svc.firewalls["stpu-default-allow-ssh"]
    assert ssh_rule["allowed"] == [
        {"IPProtocol": "tcp", "ports": ["22"]}]
    # Tag-scoped: a shared VPC's unrelated VMs are never exposed.
    assert ssh_rule["targetTags"] == [gcp._COMMON_TAG]
    # Idempotent: second call creates nothing new.
    count = len(svc.firewalls)
    gcp.bootstrap_instances("us-east5", "c1", _config())
    assert len(svc.firewalls) == count


def test_bootstrap_missing_network_is_a_clear_error(monkeypatch):
    svc = FakeComputeWithNetworks(networks=())
    monkeypatch.setattr(gcp, "compute_rest", svc)
    monkeypatch.setattr(gcp, "_gcloud_project", lambda: "testproj")
    # Project-global + permanent -> NOT retryable (a ProvisionError
    # would make the failover loop sweep every zone, or spin forever
    # under retry_until_up).
    with pytest.raises(exceptions.NoCloudAccessError,
                       match="does not exist"):
        gcp.bootstrap_instances("us-east5", "c1", _config())


def test_bootstrap_create_race_tolerated(monkeypatch):
    """Two concurrent launches on one network both POST the shared
    rule; the loser's 409 reads as already-bootstrapped, not a crash
    (GcpApiError would escape the failover loop's except)."""
    svc = FakeComputeWithNetworks()
    orig = svc.__call__

    def racy(method, path, body=None, params=None):
        if method == "POST" and path.endswith("/global/firewalls"):
            orig(method, path, body=body, params=params)  # racer wins
            raise gcp.GcpApiError(409, {"error": {
                "status": "ALREADY_EXISTS", "message": "conflict"}})
        return orig(method, path, body=body, params=params)

    monkeypatch.setattr(gcp, "compute_rest", racy)
    monkeypatch.setattr(gcp, "_gcloud_project", lambda: "testproj")
    gcp.bootstrap_instances("us-east5", "c1", _config())  # no raise
    assert any(n.endswith("allow-ssh") for n in svc.firewalls)
