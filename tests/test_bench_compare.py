"""tools/bench_compare.py: the CI gate over the bench trajectory.

The tool must fail (exit 1) on >threshold% regressions in the named
serving/training metrics, tolerate null legs (failed benches record
null) without crashing, and treat a silently dropped exact-named
headline as a regression.
"""
import importlib.util
import json
import pathlib

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "bench_compare",
    pathlib.Path(__file__).parent.parent / "tools" / "bench_compare.py")
bench_compare = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(bench_compare)


def _doc(mfu, llama_b8, prefix_tok=900.0, hit=0.95):
    return {
        "metric": "llama_train_mfu_1chip", "value": mfu, "unit": "%MFU",
        "vs_baseline": round(mfu / 40.0, 3),
        "detail": {
            "tokens_per_sec_per_chip": mfu * 200.0,
            "long_context": {"tokens_per_sec_per_chip": 14000.0,
                             "mfu_pct": 48.0},
            "eight_b_shape": {"tokens_per_sec_per_chip": 10000.0},
            "serving": {
                "llama_decode_tok_s_b8": llama_b8,
                "llama_engine_ragged_tok_s": 800.0,
                "llama_engine_prefix_tok_s": prefix_tok,
                "llama_prefix_hit_rate": hit,
            },
        },
    }


def _write(tmp_path, name, doc):
    path = tmp_path / name
    path.write_text(json.dumps(doc))
    return str(path)


def test_no_regression_exits_zero(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _doc(50.0, 1700.0))
    # +3% everywhere and a small dip well inside the 5% budget.
    new = _write(tmp_path, "new.json", _doc(51.5, 1650.0))
    assert bench_compare.main([old, new]) == 0
    out = capsys.readouterr().out
    assert "no regression" in out
    assert "llama_decode_tok_s_b8" in out


def test_regression_exits_one_and_names_the_metric(tmp_path, capsys):
    old = _write(tmp_path, "old.json", _doc(50.0, 1700.0))
    new = _write(tmp_path, "new.json", _doc(50.0, 1400.0))  # -17.6%
    assert bench_compare.main([old, new]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out
    assert "llama_decode_tok_s_b8" in out


def test_threshold_is_respected(tmp_path):
    old = _write(tmp_path, "old.json", _doc(50.0, 1700.0))
    new = _write(tmp_path, "new.json", _doc(46.0, 1700.0))  # -8% MFU
    assert bench_compare.main([old, new]) == 1
    assert bench_compare.main([old, new, "--threshold", "10"]) == 0


def test_dropped_exact_metric_fails_null_glob_skipped(tmp_path, capsys):
    old_doc = _doc(50.0, 1700.0)
    new_doc = _doc(50.0, 1700.0)
    # A failed serving leg records null — glob-selected, so skipped
    # with a note, not a crash.
    new_doc["detail"]["serving"]["llama_engine_prefix_tok_s"] = None
    assert bench_compare.main([_write(tmp_path, "a.json", old_doc),
                               _write(tmp_path, "b.json", new_doc)]) == 0
    assert "gone in new; skipped" in capsys.readouterr().out

    # The exact-named headline disappearing IS a failure.
    del new_doc["value"]
    assert bench_compare.main([_write(tmp_path, "c.json", old_doc),
                               _write(tmp_path, "d.json", new_doc)]) == 1


def test_unwraps_driver_tracked_shape(tmp_path):
    """BENCH_r*.json wraps the bench doc under "parsed"."""
    old = _write(tmp_path, "old.json",
                 {"n": 5, "rc": 0, "parsed": _doc(50.0, 1700.0)})
    new = _write(tmp_path, "new.json", _doc(50.0, 300.0))
    assert bench_compare.main([old, new]) == 1


def test_custom_metric_selection(tmp_path):
    old = _write(tmp_path, "old.json", _doc(50.0, 1700.0, hit=0.9))
    new = _write(tmp_path, "new.json", _doc(10.0, 1700.0, hit=0.89))
    # Only watching the hit rate: the MFU collapse is out of scope.
    assert bench_compare.main(
        [old, new, "--metrics", "detail.serving.*_prefix_hit_rate"]) == 0
    assert bench_compare.main(
        [old, new, "--metrics", "value"]) == 1


def test_compare_is_pure_and_orders_patterns_once():
    """compare() never double-counts a path matched by two patterns."""
    old = _doc(50.0, 1700.0)
    report, regressions = bench_compare.compare(
        old, _doc(50.0, 1700.0),
        ["value", "value", "detail.serving.*"], 5.0)
    assert not regressions
    assert len([l for l in report if " value:" in l]) == 1


# ------------------------------------------------ lower-is-better legs
def _doc_with_ckpt(mfu, save_s, restore_s=0.5):
    doc = _doc(mfu, 1700.0)
    doc["detail"]["serving"]["llama_ckpt_save_s"] = save_s
    doc["detail"]["serving"]["llama_ckpt_restore_s"] = restore_s
    return doc


def test_lower_is_better_regression_on_rise(tmp_path, capsys):
    """Checkpoint latencies regress when they go UP, not down."""
    old = _write(tmp_path, "old.json", _doc_with_ckpt(50.0, 1.0))
    worse = _write(tmp_path, "worse.json", _doc_with_ckpt(50.0, 1.5))
    assert bench_compare.main([old, worse]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION" in out and "llama_ckpt_save_s" in out
    assert "lower is better" in out

    better = _write(tmp_path, "better.json", _doc_with_ckpt(50.0, 0.3))
    assert bench_compare.main([old, better]) == 0


def test_lower_is_better_threshold_and_custom_selection(tmp_path):
    old = _write(tmp_path, "old.json", _doc_with_ckpt(50.0, 1.0))
    slightly = _write(tmp_path, "s.json", _doc_with_ckpt(50.0, 1.04))
    assert bench_compare.main([old, slightly]) == 0  # +4% < 5%
    worse = _write(tmp_path, "w.json", _doc_with_ckpt(50.0, 2.0))
    # --metrics-lower narrows the lower-is-better set.
    assert bench_compare.main(
        [old, worse, "--metrics-lower",
         "detail.serving.*_ckpt_restore_s"]) == 0
    assert bench_compare.main(
        [old, worse, "--metrics-lower",
         "detail.serving.*_ckpt_save_s"]) == 1


def test_lower_metric_absent_in_old_is_skipped(tmp_path):
    """Pre-checkpoint BENCH files (r01-r05) have no ckpt legs: the
    glob matches nothing and the compare must not fail on that."""
    old = _write(tmp_path, "old.json", _doc(50.0, 1700.0))
    new = _write(tmp_path, "new.json", _doc_with_ckpt(50.0, 99.0))
    assert bench_compare.main([old, new]) == 0


def _doc_with_tuned(mfu, tuned_tok, tag="4af7e49baa9e"):
    doc = _doc(mfu, 1700.0)
    doc["detail"]["serving"]["llama_engine_tuned_tok_s"] = tuned_tok
    doc["detail"]["serving"]["llama_engine_tuned_detail"] = {
        "engine_tuned_default_tok_s": tuned_tok * 0.9,
        "tuned_constants": {"block": 128, "prefill_chunk": 128},
        "tune_manifest": tag,
    }
    return doc


def test_tuned_leg_is_gated_by_default(tmp_path, capsys):
    """The `stpu tune` serving leg sits in DEFAULT_METRICS like the
    other engine tok/s legs — a tuned-throughput collapse (stale
    manifest on new hardware) fails CI without extra flags."""
    old = _write(tmp_path, "old.json", _doc_with_tuned(50.0, 1000.0))
    worse = _write(tmp_path, "worse.json", _doc_with_tuned(50.0, 700.0))
    assert bench_compare.main([old, worse]) == 1
    assert "llama_engine_tuned_tok_s" in capsys.readouterr().out
    same = _write(tmp_path, "same.json", _doc_with_tuned(50.0, 990.0))
    assert bench_compare.main([old, same]) == 0


def test_manifest_flag_reports_and_pins_provenance(tmp_path, capsys):
    """--manifest prints which tuning manifest each round ran with;
    --manifest TAG additionally pins the NEW round to that manifest
    (a CI round silently tuned by an unreviewed manifest fails)."""
    old = _write(tmp_path, "old.json",
                 _doc_with_tuned(50.0, 1000.0, tag="aaaa00000000"))
    new = _write(tmp_path, "new.json",
                 _doc_with_tuned(50.0, 1000.0, tag="bbbb11111111"))
    # Bare flag: provenance lines, no gating.
    assert bench_compare.main([old, new, "--manifest"]) == 0
    out = capsys.readouterr().out
    assert "aaaa00000000 -> bbbb11111111" in out
    # Pinned to the new round's actual tag: passes.
    assert bench_compare.main([old, new, "--manifest",
                               "bbbb11111111"]) == 0
    # Pinned to something else: the mismatch is fatal.
    assert bench_compare.main([old, new, "--manifest",
                               "aaaa00000000"]) == 1
    assert "bbbb11111111" in capsys.readouterr().err
    # Pinning a round with NO tuned legs recorded is also fatal.
    bare = _write(tmp_path, "bare.json", _doc(50.0, 1700.0))
    assert bench_compare.main([old, bare, "--manifest",
                               "aaaa00000000"]) == 1


def test_manifest_tags_extractor_shapes():
    assert bench_compare.manifest_tags(_doc(50.0, 1700.0)) == {}
    # Driver-tracked wrapper shape unwraps like compare() does.
    assert bench_compare.manifest_tags(
        {"n": 1, "rc": 0,
         "parsed": _doc_with_tuned(50.0, 900.0, tag="cafe12345678")}
    ) == {"llama": "cafe12345678"}


def test_lower_pattern_wins_polarity_overlap(tmp_path):
    """A broad higher-is-better glob must not claim latency paths away
    from the lower-is-better set (polarity inversion)."""
    old = _write(tmp_path, "old.json", _doc_with_ckpt(50.0, 1.0))
    worse = _write(tmp_path, "worse.json", _doc_with_ckpt(50.0, 2.0))
    # detail.serving.* overlaps llama_ckpt_save_s; the rise must still
    # be a regression (and the symmetric drop must still pass).
    assert bench_compare.main(
        [old, worse, "--metrics", "detail.serving.*"]) == 1
    better = _write(tmp_path, "better.json", _doc_with_ckpt(50.0, 0.4))
    assert bench_compare.main(
        [old, better, "--metrics", "detail.serving.*"]) == 0
