"""Distributed tracing: span subsystem + end-to-end trace reassembly.

ISSUE 5 acceptance pinned here:
  * one request driven LB -> replica -> decode engine with tracing
    armed reassembles into a SINGLE trace tree: LB root carrying
    retry/policy annotations, replica child, engine queue/prefill/
    decode grandchildren;
  * ``stpu trace export --perfetto`` on that trace emits Chrome
    trace-event JSON with ph/ts/dur/pid/tid fields;
  * unarmed, the LB request path and the engine step never touch the
    tracing module beyond the ENABLED flag check (mirror of the
    fault-injection zero-cost guarantee).
"""
import json
import socket
import threading
import time
import urllib.request

import pytest
from click.testing import CliRunner

from skypilot_tpu.observability import tracing


@pytest.fixture
def armed(tmp_state_dir):
    tracing.arm(sample=1.0)
    yield tmp_state_dir
    tracing.disarm()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _tiny_llm():
    import jax

    from skypilot_tpu.models import llama
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    return cfg, params


# ------------------------------------------------------------- span unit
def test_span_lifecycle_and_record(armed):
    span = tracing.start_span("unit.root", kind="test",
                              attrs={"k": "v"})
    span.event("mark", detail=1)
    span.set_attr("k2", 2)
    with tracing.start_span("unit.child", parent=span) as child:
        child_id = child.span_id
    span.end(status="ok", bytes=7)
    span.end(status="error")   # idempotent: second end is a no-op
    recs = tracing.read(trace_id=span.trace_id)
    assert len(recs) == 2
    by_name = {r["name"]: r for r in recs}
    root = by_name["unit.root"]
    assert root["parent_id"] is None
    assert root["status"] == "ok"                 # not overwritten
    assert root["attrs"] == {"k": "v", "k2": 2, "bytes": 7}
    assert root["dur"] >= 0
    assert root["events"][0]["name"] == "mark"
    assert root["events"][0]["at"] >= 0
    assert root["run_id"]
    child = by_name["unit.child"]
    assert child["parent_id"] == root["span_id"]
    assert child["span_id"] == child_id
    assert child["trace_id"] == root["trace_id"]


def test_context_wire_roundtrip():
    ctx = tracing.SpanContext("ab" * 16, "cd" * 8, True)
    wire = tracing.format_ctx(ctx)
    back = tracing.parse_ctx(wire)
    assert (back.trace_id, back.span_id, back.sampled) == \
        (ctx.trace_id, ctx.span_id, True)
    unsampled = tracing.parse_ctx(tracing.format_ctx(
        tracing.SpanContext("ab" * 16, "cd" * 8, False)))
    assert unsampled.sampled is False
    # Garbage never raises — a hostile header must not 500 the LB.
    for bad in (None, "", "zz", "deadbeef-cafe-01", "x" * 200):
        assert tracing.parse_ctx(bad) is None
    assert tracing.extract({tracing.HEADER: wire}).span_id == \
        ctx.span_id
    assert tracing.extract({}) is None


def test_env_carrier_and_adoption(armed, monkeypatch):
    monkeypatch.setenv(tracing.ENV_CTX, "sentinel")  # restored after
    span = tracing.start_span("launch.root", kind="jobs")
    tracing.set_env_context(span.context())
    got = tracing.from_env()
    assert got.trace_id == span.trace_id
    assert got.span_id == span.span_id
    child_env = tracing.child_env()
    assert child_env[tracing.ENABLE_ENV] == "1"
    assert tracing.parse_ctx(child_env[tracing.ENV_CTX]).trace_id == \
        span.trace_id
    span.end()
    # adopt_ctx (gang-driver side): a spec-carried context re-arms
    # tracing and re-exports the env for the driver's own children.
    tracing.disarm()
    ctx = tracing.adopt_ctx(tracing.format_ctx(span.context()))
    assert tracing.ENABLED and ctx.trace_id == span.trace_id
    assert tracing.from_env().span_id == span.span_id
    # Junk never arms.
    tracing.disarm()
    assert tracing.adopt_ctx("not-a-context") is None
    assert not tracing.ENABLED


def test_sampling_root_decision_child_inheritance(armed):
    tracing.arm(sample=0.0)
    # An unsampled root records nothing but still CARRIES the negative
    # decision: its context serializes with the 00 flag, so the next
    # hop (armed replica) does NOT open its own root — traces are
    # whole or absent, never torn at a process boundary.
    root = tracing.start_span("unsampled.root")
    ctx = root.context()
    assert ctx is not None and ctx.sampled is False
    assert tracing.format_ctx(ctx).endswith("-00")
    root.event("e")
    root.end()
    child = tracing.start_span("downstream.hop", parent=ctx)
    assert child.context().sampled is False       # decision inherited
    assert child.context().trace_id == ctx.trace_id
    child.end()
    tracing.record_span("downstream.phase", "test", child.context(),
                        start_mono=0.0, end_mono=1.0)
    import pathlib
    assert not pathlib.Path(tracing.trace_path()).exists()
    # A sampled inbound context overrides the local rate the same way:
    # the decision was made at the root, the trace must stay whole.
    inbound = tracing.SpanContext("ef" * 16, "ab" * 8, True)
    span = tracing.start_span("sampled.child", parent=inbound)
    assert span is not tracing.NOOP
    span.end()
    assert tracing.read(trace_id="ef" * 16)


def test_disabled_writes_nothing(tmp_state_dir):
    assert not tracing.ENABLED
    span = tracing.start_span("off.root")
    assert span is tracing.NOOP
    span.event("e")
    span.end()
    tracing.record_span("off.retro", "test", None, start_mono=0.0)
    import pathlib
    assert not pathlib.Path(tracing.trace_path()).exists()


def test_record_span_retroactive(armed):
    parent = tracing.start_span("retro.parent")
    t0 = time.perf_counter()
    time.sleep(0.02)
    t1 = time.perf_counter()
    tracing.record_span("retro.phase", "test", parent.context(),
                        start_mono=t0, end_mono=t1,
                        attrs={"n": 3}, events=[{"name": "e", "at": 0}])
    parent.end()
    recs = tracing.read(trace_id=parent.trace_id)
    phase = next(r for r in recs if r["name"] == "retro.phase")
    assert abs(phase["dur"] - (t1 - t0)) < 1e-6
    assert phase["parent_id"] == parent.span_id
    # Reconstructed wall start sits inside the parent's window.
    root = next(r for r in recs if r["name"] == "retro.parent")
    assert root["ts"] - 0.5 <= phase["ts"] <= root["ts"] + root["dur"]


def test_assemble_orphans_surface_as_roots(armed):
    span = tracing.start_span("orphan.child", parent=tracing.SpanContext(
        "aa" * 16, "bb" * 8, True))
    span.end()
    roots = tracing.assemble("aa" * 16)
    assert len(roots) == 1                 # parent record never landed
    assert roots[0]["span"]["name"] == "orphan.child"


# ----------------------------------------------------- launch carriers
def test_gang_env_carries_trace_context(armed, monkeypatch):
    """The gang driver's host environments carry STPU_TRACE_CTX +
    STPU_TRACE (the STPU_RUN_ID pattern), so job-side spans nest under
    the gang span; unarmed, the host env is untouched."""
    monkeypatch.setenv(tracing.ENV_CTX, "placeholder")  # restored
    from skypilot_tpu.agent import gang_exec
    span = tracing.start_span("gang.run", kind="gang")
    tracing.set_env_context(span.context())
    spec = {"node_ips": ["10.0.0.1", "10.0.0.2"],
            "hosts": [{"kind": "ssh"}, {"kind": "ssh"}],
            "task_id": "t1", "cluster_name": "c1",
            "envs": {}}
    env = gang_exec._build_env(spec, rank=1)
    assert env[tracing.ENABLE_ENV] == "1"
    assert tracing.parse_ctx(env[tracing.ENV_CTX]).span_id == \
        span.span_id
    span.end()
    # The backend stamps the same context into the gang job spec.
    from skypilot_tpu.observability import tracing as t2
    assert t2.env_context() == tracing.format_ctx(span.context())
    tracing.disarm()
    assert tracing.env_context() is None      # stale env can't leak
    env = gang_exec._build_env(spec, rank=0)
    assert tracing.ENABLE_ENV not in env
    assert tracing.ENV_CTX not in env


# ----------------------------------------------------------- e2e + CLI
def _walk(nodes):
    for node in nodes:
        yield node
        yield from _walk(node["children"])


@pytest.mark.usefixtures("tmp_state_dir")
def test_trace_e2e_lb_replica_engine():
    """The acceptance story: request → LB (dead replica first: retry)
    → live replica → decode engine, reassembled into ONE tree; then
    `stpu trace export --perfetto` on it."""
    from skypilot_tpu import cli as cli_mod
    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import \
        RoundRobinPolicy

    tracing.arm(sample=1.0)
    cfg, params = _tiny_llm()
    ready = threading.Event()
    httpd = serve_llm.serve(cfg, params, 0, ready_event=ready)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    assert ready.wait(timeout=300)
    replica = f"http://127.0.0.1:{httpd.server_address[1]}"
    dead = f"http://127.0.0.1:{_free_port()}"
    policy = RoundRobinPolicy()
    # Dead replica FIRST: round-robin's first pick fails pre-first-byte
    # and the retry lands on the live one — a real retry annotation.
    policy.set_ready_replicas([dead, replica])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    lb_url = f"http://127.0.0.1:{lb.server_address[1]}"

    def generate(payload):
        req = urllib.request.Request(
            lb_url + "/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, resp.read()

    try:
        status, body = generate({"prompt": [1, 2, 3], "max_tokens": 4})
        assert status == 200
        assert len(json.loads(body)["tokens"]) == 4

        # Span records land as each side's span ENDS (the LB root and
        # replica span close after the response bytes are out) — poll
        # for the complete tree.
        tree = None
        deadline = time.time() + 20
        while time.time() < deadline:
            rows = [r for r in tracing.list_traces()
                    if r["name"] == "lb.request"]
            if rows:
                roots = tracing.assemble(rows[0]["trace_id"])
                if sum(1 for _ in _walk(roots)) >= 6:
                    tree = roots
                    break
            time.sleep(0.05)
        assert tree is not None, "trace never completed"
        assert len(tree) == 1                    # a SINGLE tree
        root = tree[0]["span"]
        assert root["name"] == "lb.request"
        assert root["parent_id"] is None
        assert root["attrs"]["code"] == "200"

        # Retry + policy annotations on the LB root.
        ev = root["events"]
        names = [e["name"] for e in ev]
        assert "retry" in names and "upstream_failed" in names
        selects = [e for e in ev if e["name"] == "select"]
        assert [s["target"] for s in selects] == [dead, replica]
        assert selects[0]["policy"] == "RoundRobinPolicy"
        assert selects[1]["attempt"] == 1

        # Replica child, engine grandchildren.
        gen = [c for c in tree[0]["children"]
               if c["span"]["name"] == "replica.generate"]
        assert len(gen) == 1
        assert gen[0]["span"]["attrs"]["prompt_tokens"] == 3
        engine_spans = {c["span"]["name"]: c["span"]
                       for c in gen[0]["children"]}
        assert {"engine.queue", "engine.prefill",
                "engine.decode"} <= set(engine_spans)
        assert engine_spans["engine.prefill"]["attrs"][
            "steps_to_first_token"] >= 1
        assert engine_spans["engine.decode"]["attrs"]["tokens"] == 4
        # Every span shares the one trace id.
        assert all(n["span"]["trace_id"] == root["trace_id"]
                   for n in _walk(tree))

        # Critical path runs root -> replica -> an engine span.
        cp = tracing.critical_path(tree[0])
        assert cp[0] == root["span_id"]
        assert len(cp) == 3

        # A streamed request additionally records stream delivery.
        status, body = generate({"prompt": [1, 2, 3], "max_tokens": 3,
                                 "stream": True})
        assert status == 200 and b"[DONE]" in body
        deadline = time.time() + 20
        stream_rec = stream_tree = None
        while time.time() < deadline:
            recs = [r for r in tracing.read()
                    if r["name"] == "replica.stream"]
            if recs:
                stream_rec = recs[0]
                # The LB root lands last (it ends after the replica) —
                # wait for the tree to be complete.
                roots = tracing.assemble(stream_rec["trace_id"])
                if len(roots) == 1 and \
                        roots[0]["span"]["name"] == "lb.request":
                    stream_tree = roots
                    break
            time.sleep(0.05)
        assert stream_rec is not None
        assert stream_rec["attrs"]["tokens"] == 3
        assert stream_tree is not None, "stream trace never completed"

        # ------------------------------------------------ CLI surface
        runner = CliRunner()
        result = runner.invoke(cli_mod.cli, ["trace", "list"])
        assert result.exit_code == 0, result.output
        assert root["trace_id"] in result.output

        # Abbreviated id + indented tree + critical-path marker.
        result = runner.invoke(
            cli_mod.cli,
            ["trace", "show", root["trace_id"][:10], "--events"])
        assert result.exit_code == 0, result.output
        assert "lb.request" in result.output
        assert "  replica.generate" in result.output   # indented child
        assert "engine.prefill" in result.output
        assert "*" in result.output                    # critical path
        assert "retry" in result.output                # annotation

        # Perfetto export: Chrome trace-event JSON with the fields
        # chrome://tracing validates (ph/ts/dur/pid/tid).
        result = runner.invoke(
            cli_mod.cli,
            ["trace", "export", "--perfetto", root["trace_id"]])
        assert result.exit_code == 0, result.output
        doc = json.loads(result.output)
        events = doc["traceEvents"]
        assert events
        for e in events:
            assert e["ph"] in ("X", "i")
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["pid"], int)
            assert isinstance(e["tid"], int)
            assert e["name"]
        complete = [e for e in events if e["ph"] == "X"]
        assert {e["name"] for e in complete} >= {
            "lb.request", "replica.generate", "engine.queue",
            "engine.prefill", "engine.decode"}
        assert all(isinstance(e["dur"], (int, float)) and e["dur"] >= 0
                   for e in complete)
        # Span annotations ride along as instant events.
        assert any(e["name"] == "lb.request.retry" for e in events)
    finally:
        tracing.disarm()
        lb.shutdown()
        httpd.engine.shutdown()
        httpd.shutdown()


# ------------------------------------------------------ overhead guard
@pytest.mark.usefixtures("tmp_state_dir")
def test_tracing_unarmed_zero_cost(monkeypatch):
    """Mirror of the fault-injection zero-cost guarantee: with tracing
    unarmed, the full LB proxy path and the engine submit/prefill/
    decode path never reach the tracing module past the ENABLED flag —
    any start_span/record_span call trips the monkeypatched bomb."""
    import http.server
    import socketserver

    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.decode_engine import DecodeEngine
    from skypilot_tpu.serve.load_balancing_policies import \
        RoundRobinPolicy

    assert not tracing.ENABLED

    def bomb(*args, **kwargs):
        raise AssertionError(
            "tracing reached while unarmed (hot path must guard on "
            "tracing.ENABLED)")

    monkeypatch.setattr(tracing, "start_span", bomb)
    monkeypatch.setattr(tracing, "record_span", bomb)

    class _Ok(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class _Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    upstream = _Srv(("127.0.0.1", 0), _Ok)
    threading.Thread(target=upstream.serve_forever,
                     daemon=True).start()
    policy = RoundRobinPolicy()
    policy.set_ready_replicas(
        [f"http://127.0.0.1:{upstream.server_address[1]}"])
    lb = lb_lib.run_load_balancer(0, policy, lb_lib.RequestRecorder())
    try:
        url = f"http://127.0.0.1:{lb.server_address[1]}/x"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
    finally:
        lb.shutdown()
        upstream.shutdown()

    # Engine path: admission, chunked prefill, decode steps, slot free.
    cfg, params = _tiny_llm()
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8).start()
    try:
        toks = engine.submit([1, 2, 3], max_tokens=4).result(
            timeout=600)
        assert len(toks) == 4
    finally:
        engine.shutdown()


def test_engine_step_is_tracing_free():
    """The batched decode step — the per-token hot path — carries NO
    tracing code even when armed: engine spans ride request edges
    (admission, prefill completion, slot free), never the step."""
    import inspect

    from skypilot_tpu.serve import decode_engine
    assert "tracing" not in inspect.getsource(
        decode_engine.DecodeEngine._decode_step)
    assert "tracing" not in inspect.getsource(decode_engine._engine_step)


@pytest.mark.slow
@pytest.mark.usefixtures("tmp_state_dir")
def test_engine_throughput_armed_vs_unarmed_within_noise():
    """Armed tracing records a handful of spans per REQUEST, never
    per-token work — decode throughput must stay within noise of the
    unarmed engine (generous CPU-CI bound; the bench harness's
    measure_engine_ragged reports `traced` for the TPU-side check)."""
    from skypilot_tpu.serve.decode_engine import DecodeEngine

    cfg, params = _tiny_llm()

    def run(trace_root):
        engine = DecodeEngine(cfg, params, slots=4, max_seq=96,
                              prefill_chunk=16).start()
        try:
            engine.warmup()
            t0 = time.perf_counter()
            reqs = [engine.submit([1 + i, 2, 3, 4], max_tokens=24,
                                  trace=trace_root)
                    for i in range(8)]
            total = sum(len(r.result(timeout=600)) for r in reqs)
            return total / (time.perf_counter() - t0)
        finally:
            engine.shutdown()

    cold = run(None)               # warm the jit caches once, discard
    del cold
    unarmed = run(None)
    tracing.arm(sample=1.0)
    try:
        root = tracing.start_span("bench.root", kind="bench")
        armed = run(root.context())
        root.end()
    finally:
        tracing.disarm()
    # Spans were actually recorded (the armed leg measured something).
    assert any(r["name"] == "engine.decode" for r in tracing.read())
    assert armed >= 0.5 * unarmed, (armed, unarmed)
