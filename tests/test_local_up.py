"""`stpu local up/down` — hermetic (fake kind/kubectl seam) plus an
opt-in ``--kind-live`` smoke that exercises the kubernetes provider
end-to-end against a real Kind cluster when the binaries exist.

Reference analog: `sky local up` (sky/cli.py:5054-5185).
"""
import shutil

import pytest
from click.testing import CliRunner

from skypilot_tpu import cli as cli_mod
from skypilot_tpu import exceptions
from skypilot_tpu.utils import local_up


# ----------------------------------------------------------- hermetic
class FakeKind:
    def __init__(self):
        self.clusters = set()
        self.calls = []

    def __call__(self, argv, timeout=600):
        self.calls.append(argv)
        if argv[:2] == ["kind", "get"]:
            return 0, "\n".join(sorted(self.clusters))
        if argv[:3] == ["kind", "create", "cluster"]:
            self.clusters.add(argv[argv.index("--name") + 1])
            return 0, "Creating cluster ..."
        if argv[:3] == ["kind", "delete", "cluster"]:
            self.clusters.discard(argv[argv.index("--name") + 1])
            return 0, "Deleted"
        if argv[0] == "kubectl":
            return 0, "node/stpu-local-control-plane Ready"
        raise AssertionError(f"unexpected argv {argv}")


@pytest.fixture
def fake_kind(monkeypatch):
    fake = FakeKind()
    monkeypatch.setattr(local_up, "_run", fake)
    monkeypatch.setattr(local_up, "_which", lambda b: f"/usr/bin/{b}")
    return fake


def test_local_up_creates_and_adopts(fake_kind):
    assert local_up.up() == "kind-stpu-local"
    assert "stpu-local" in fake_kind.clusters
    n_calls = len(fake_kind.calls)
    # Second up adopts: no second create.
    assert local_up.up() == "kind-stpu-local"
    assert not any(c[:3] == ["kind", "create", "cluster"]
                   for c in fake_kind.calls[n_calls:])
    local_up.down()
    assert "stpu-local" not in fake_kind.clusters


def test_local_up_missing_binaries(monkeypatch):
    monkeypatch.setattr(local_up, "_which", lambda b: None)
    with pytest.raises(exceptions.SkyTpuError, match="missing kind"):
        local_up.up()


def test_cli_local_up_down(fake_kind):
    r = CliRunner().invoke(cli_mod.cli, ["local", "up"])
    assert r.exit_code == 0, r.output
    assert "context kind-stpu-local" in r.output
    assert "cloud: kubernetes" in r.output
    r = CliRunner().invoke(cli_mod.cli, ["local", "down"])
    assert r.exit_code == 0, r.output


# ----------------------------------------------------------- live leg
@pytest.mark.kind_live
@pytest.mark.timeout(1200)
def test_kind_launch_exec_down_live(tmp_state_dir):
    """Real Kind cluster: launch -> exec -> down through the kubernetes
    provider (single pod; the slim default image needs no sshd)."""
    if any(shutil.which(b) is None for b in ("kind", "kubectl",
                                             "docker")):
        pytest.skip("kind/kubectl/docker not on PATH")
    from skypilot_tpu import core, execution
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    local_up.up("stpu-test-live")
    try:
        task = Task("kind-smoke", run="echo kind-says-$((6*7))")
        task.set_resources(Resources(cloud="kubernetes"))
        job_id, handle = execution.launch(task,
                                          cluster_name="kind-smoke-c")
        assert handle is not None
        core.tail_logs("kind-smoke-c", job_id)
        core.down("kind-smoke-c")
    finally:
        local_up.down("stpu-test-live")
