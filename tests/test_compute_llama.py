"""Hermetic compute tests on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer


def test_mesh_construction():
    m = mesh_lib.make_mesh({"dp": 2, "tp": 4})
    assert m.shape == {"dp": 2, "tp": 4}
    m2 = mesh_lib.make_mesh({"dp": -1, "tp": 2})
    assert m2.shape["dp"] == 4


def test_sharding_rules_drop_absent_axes():
    m = mesh_lib.make_mesh({"dp": 2, "tp": 4})
    rules = mesh_lib.DEFAULT_RULES
    spec = rules.spec(("batch", "act_seq", "heads"), m)
    # fsdp/sp absent from mesh -> batch maps to ('dp',), act_seq drops.
    assert spec == jax.sharding.PartitionSpec("dp", None, "tp")


def test_llama_forward_shapes():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    logits = llama.forward(cfg, params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_llama_causality():
    """Changing a future token must not change past logits."""
    cfg = llama.LlamaConfig.tiny()
    cfg = llama.LlamaConfig.tiny()
    params = llama.init(cfg, jax.random.key(0))
    t1 = jnp.array([[1, 2, 3, 4, 5, 6, 7, 8]], dtype=jnp.int32)
    t2 = t1.at[0, 5].set(9)
    l1 = llama.forward(cfg, params, t1)
    l2 = llama.forward(cfg, params, t2)
    np.testing.assert_allclose(l1[0, :5], l2[0, :5], rtol=2e-2, atol=2e-3)
    assert not np.allclose(l1[0, 5:], l2[0, 5:], atol=1e-4)


def test_train_step_decreases_loss_sharded():
    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    mesh = mesh_lib.make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    rules = mesh_lib.DEFAULT_RULES
    params = llama.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(trainer.TrainConfig(
        learning_rate=1e-2, warmup_steps=1, total_steps=50))
    state = trainer.init_train_state(params, tx)

    shardings = trainer.state_shardings(
        mesh, rules, llama.param_specs(cfg),
        jax.eval_shape(lambda: state))
    state = jax.device_put(state, shardings)

    step = trainer.make_train_step(
        lambda p, t, constrain: llama.forward(cfg, p, t,
                                              constrain=constrain),
        tx, mesh, rules)
    tokens = jax.random.randint(jax.random.key(1), (4, 32), 0, 64)
    batch = {"tokens": tokens}
    state, m0 = step(state, batch)
    for _ in range(10):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"])
    # params actually sharded: embed spec ("vocab","embed") -> (tp, fsdp).
    emb_shard = state.params["embed"].sharding
    assert emb_shard.spec == jax.sharding.PartitionSpec("tp", "fsdp")


def test_kv_cache_decode_matches_full_forward():
    """Cached incremental decode (prefill + per-token steps) must produce
    exactly the greedy continuation that full-recompute forward gives —
    including with a right-padded prompt bucket."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from skypilot_tpu.models import llama

    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    b, s, mt = 2, 13, 6
    prompt = jax.random.randint(jax.random.key(1), (b, s), 1, 128)

    # Reference: recompute the full prefix per token.
    buf = jnp.zeros((b, s + mt), jnp.int32).at[:, :s].set(prompt)
    ref = []
    for i in range(mt):
        logits = llama.forward(cfg, params, buf[:, :s + i])
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        buf = buf.at[:, s + i].set(nxt)
        ref.append(nxt)
    ref = jnp.stack(ref, axis=1)

    # Cached, with the prompt right-padded to a bucket of 16.
    padded = jnp.zeros((b, 16), jnp.int32).at[:, :s].set(prompt)
    got = llama.greedy_decode(cfg, params, padded, jnp.int32(s), mt,
                              max_seq=16 + mt)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_multislice_mesh_and_train_step():
    """Hybrid DCN x ICI mesh: dp crosses slices, fsdp within; a train
    step compiles and runs with DEFAULT_RULES on the virtual mesh."""
    import jax
    import jax.numpy as jnp
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import trainer

    mesh = mesh_lib.make_multislice_mesh({"fsdp": -1}, num_slices=2)
    assert mesh.axis_names == ("dp", "fsdp")
    assert mesh.shape["dp"] == 2 and mesh.shape["fsdp"] == 4

    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    tx = trainer.make_optimizer(
        trainer.TrainConfig(warmup_steps=1, total_steps=10))
    state = trainer.init_train_state(params, tx)
    state = jax.device_put(state, trainer.state_shardings(
        mesh, mesh_lib.DEFAULT_RULES, llama.param_specs(cfg), state))
    step = trainer.make_train_step(
        lambda p, t, constrain: llama.forward(cfg, p, t,
                                              constrain=constrain),
        tx, mesh, mesh_lib.DEFAULT_RULES)
    tokens = jax.random.randint(jax.random.key(1), (8, 64), 0, 128)
    state, metrics = step(state, {"tokens": tokens})
    assert jnp.isfinite(metrics["loss"]).item()

    # Error paths: indivisible slices, dcn/ici name clash.
    import pytest
    with pytest.raises(ValueError, match="divisible"):
        mesh_lib.make_multislice_mesh({"fsdp": -1}, num_slices=3)
    with pytest.raises(ValueError, match="also named"):
        mesh_lib.make_multislice_mesh({"dp": -1}, num_slices=2)


def test_make_mesh_from_env(monkeypatch):
    from skypilot_tpu.train import distributed
    monkeypatch.setenv("SKYPILOT_NUM_SLICES", "2")
    mesh = distributed.make_mesh_from_env({"fsdp": -1})
    assert mesh.axis_names == ("dp", "fsdp") and mesh.shape["dp"] == 2
    monkeypatch.setenv("SKYPILOT_NUM_SLICES", "1")
    mesh = distributed.make_mesh_from_env({"fsdp": -1})
    assert mesh.axis_names == ("fsdp",)


def test_chunked_ce_matches_classic():
    """chunked_cross_entropy_loss (fused head+CE, logits never
    materialized) must agree with the classic full-logits loss in value
    AND gradients — including a non-chunk-divisible sequence (pad+mask
    path) and a loss mask."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from skypilot_tpu.train import trainer

    b, s, d, v = 2, 9, 16, 37   # s=9 exercises padding (CE_CHUNK > s)
    key = jax.random.key(0)
    hidden = jax.random.normal(key, (b, s, d), dtype=jnp.float32)
    head = jax.random.normal(jax.random.key(1), (d, v),
                             dtype=jnp.float32)
    targets = jax.random.randint(jax.random.key(2), (b, s), 0, v)
    mask = (jax.random.uniform(jax.random.key(3), (b, s)) > 0.3)

    def classic(hidden, head):
        logits = hidden @ head
        return trainer.cross_entropy_loss(logits, targets, mask)

    def chunked(hidden, head):
        return trainer.chunked_cross_entropy_loss(hidden, head, targets,
                                                  mask)

    old = trainer.CE_CHUNK
    trainer.CE_CHUNK = 4          # force multiple chunks + padding
    try:
        lc, gc = jax.value_and_grad(classic, argnums=(0, 1))(hidden,
                                                             head)
        lk, gk = jax.value_and_grad(chunked, argnums=(0, 1))(hidden,
                                                             head)
    finally:
        trainer.CE_CHUNK = old
    np.testing.assert_allclose(float(lc), float(lk), rtol=1e-5)
    for a, b_ in zip(gc, gk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-5)


def test_adafactor_optimizer_trains():
    """TrainConfig(optimizer='adafactor') builds a working optimizer
    (factored second moment — the 8B-shape depth enabler)."""
    import jax
    import jax.numpy as jnp
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import trainer

    cfg = llama.LlamaConfig.tiny(vocab_size=64)
    tx = trainer.make_optimizer(trainer.TrainConfig(
        warmup_steps=1, total_steps=50, learning_rate=1e-2,
        optimizer="adafactor"))
    state = trainer.init_train_state(llama.init(cfg, jax.random.key(0)),
                                     tx)
    mesh = mesh_lib.make_mesh({"dp": -1})
    step = trainer.make_train_step(
        lambda p, t, constrain: llama.forward(cfg, p, t,
                                              constrain=constrain),
        tx, mesh, mesh_lib.DEFAULT_RULES)
    batch = {"tokens": jax.random.randint(jax.random.key(1), (2, 64),
                                          0, 64)}
    state, m0 = step(state, batch)
    for _ in range(12):
        state, m = step(state, batch)
    assert float(m["loss"]) < float(m0["loss"])
