"""Observability subsystem: metrics registry, event log, clock lint,
and the serve LB's /metrics end to end.

Covers the PR-1 acceptance bar: registry concurrency, Prometheus
exposition golden text, LB /metrics histogram counts matching proxied
request counts (with the controller's autoscaler/replica metrics riding
the /sync snapshot), the autoscaler decision history, the timeline
NTP-step fix (the clock lint now lives in tests/test_static_analysis.py).
"""
import json
import threading
import time
import urllib.request

import pytest
from click.testing import CliRunner

from skypilot_tpu.observability import events
from skypilot_tpu.observability import metrics


# ------------------------------------------------------------- registry
def test_counter_concurrent_increments():
    reg = metrics.Registry()
    counter = reg.counter("hits_total", "Hits.", ("tenant",))
    n_threads, per_thread = 8, 2000

    def worker():
        child = counter.labels(tenant="a")
        for _ in range(per_thread):
            child.inc()

    threads = [threading.Thread(target=worker)
               for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert counter.labels(tenant="a").get() == n_threads * per_thread


def test_histogram_concurrent_observes_consistent():
    reg = metrics.Registry()
    hist = reg.histogram("lat", "L.", buckets=(1.0, 10.0))

    def worker():
        for i in range(1000):
            hist.observe(0.5 if i % 2 else 5.0)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    cumulative, total, count = hist.labels().snapshot()
    assert count == 4000
    assert cumulative[-1] == 4000          # +Inf bucket sees all
    assert cumulative[0] == 2000           # le=1.0
    assert total == pytest.approx(2000 * 0.5 + 2000 * 5.0)


def test_exposition_golden():
    """Exact Prometheus text format 0.0.4 output."""
    reg = metrics.Registry()
    c = reg.counter("stpu_requests_total", "Requests.",
                    ("method", "code"))
    c.labels(method="GET", code="200").inc(3)
    c.labels(method="POST", code="502").inc()
    g = reg.gauge("stpu_replicas", "Replicas.", ("state",))
    g.labels(state="READY").set(2)
    h = reg.histogram("stpu_latency_seconds", "Latency.",
                      buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(7.0)
    assert reg.render() == """\
# HELP stpu_latency_seconds Latency.
# TYPE stpu_latency_seconds histogram
stpu_latency_seconds_bucket{le="0.1"} 1
stpu_latency_seconds_bucket{le="1"} 2
stpu_latency_seconds_bucket{le="+Inf"} 3
stpu_latency_seconds_sum 7.55
stpu_latency_seconds_count 3
# HELP stpu_replicas Replicas.
# TYPE stpu_replicas gauge
stpu_replicas{state="READY"} 2
# HELP stpu_requests_total Requests.
# TYPE stpu_requests_total counter
stpu_requests_total{method="GET",code="200"} 3
stpu_requests_total{method="POST",code="502"} 1
"""


def test_label_escaping_and_validation():
    reg = metrics.Registry()
    c = reg.counter("esc_total", "E.", ("msg",))
    c.labels(msg='a"b\\c\nd').inc()
    text = reg.render()
    assert r'msg="a\"b\\c\nd"' in text
    with pytest.raises(ValueError):
        c.labels("x", "y")            # wrong arity
    with pytest.raises(ValueError):
        reg.gauge("esc_total", "conflict")  # name/type clash


def test_merge_text_drops_duplicate_families():
    """Two processes can register the same family (controller imports
    the LB module); the merged /metrics document must keep exactly one
    copy or Prometheus rejects the whole scrape."""
    a = metrics.Registry()
    a.counter("shared_total", "S.").inc(5)
    a.gauge("lb_only", "L.").set(1)
    b = metrics.Registry()
    b.counter("shared_total", "S.")             # zero-valued twin
    b.gauge("ctl_only", "C.").set(9)
    merged = metrics.merge_text(a.render(), b.render())
    assert merged.count("# HELP shared_total") == 1
    assert "shared_total 5" in merged           # live copy wins
    assert "ctl_only 9" in merged and "lb_only 1" in merged
    # Fully-duplicate extra degenerates to the primary document.
    assert metrics.merge_text(a.render(), a.render()) == a.render()


def test_exposition_edge_cases_golden():
    """Exact text for the exposition corners Prometheus is strict
    about: label escaping (quotes/backslashes/newlines), LABELED
    histogram series, and +Inf rendering in both the le label and an
    inf sum."""
    import math
    reg = metrics.Registry()
    h = reg.histogram("edge_seconds", "Edge.", ("svc",),
                      buckets=(0.5,))
    weird = 'a"b\\c\nd'
    h.labels(svc=weird).observe(0.25)
    h.labels(svc=weird).observe(math.inf)   # lands in +Inf, sum = inf
    h.labels(svc="plain").observe(2.0)
    assert reg.render() == (
        '# HELP edge_seconds Edge.\n'
        '# TYPE edge_seconds histogram\n'
        'edge_seconds_bucket{svc="a\\"b\\\\c\\nd",le="0.5"} 1\n'
        'edge_seconds_bucket{svc="a\\"b\\\\c\\nd",le="+Inf"} 2\n'
        'edge_seconds_sum{svc="a\\"b\\\\c\\nd"} +Inf\n'
        'edge_seconds_count{svc="a\\"b\\\\c\\nd"} 2\n'
        'edge_seconds_bucket{svc="plain",le="0.5"} 0\n'
        'edge_seconds_bucket{svc="plain",le="+Inf"} 1\n'
        'edge_seconds_sum{svc="plain"} 2\n'
        'edge_seconds_count{svc="plain"} 1\n')


def test_gauge_negative_infinity_and_float_rendering():
    import math
    reg = metrics.Registry()
    g = reg.gauge("edge_gauge", "G.", ("k",))
    g.labels(k="neg_inf").set(-math.inf)
    g.labels(k="frac").set(0.125)
    text = reg.render()
    assert 'edge_gauge{k="neg_inf"} -Inf' in text
    assert 'edge_gauge{k="frac"} 0.125' in text


def test_dump_to_file_atomic(tmp_path):
    reg = metrics.Registry()
    reg.gauge("g", "G.").set(4)
    target = tmp_path / "out.prom"
    metrics.dump_to_file(target, reg)
    assert target.read_text() == reg.render()
    assert not (tmp_path / "out.prom.tmp").exists()
    # Unwritable destination is swallowed, never raised.
    metrics.dump_to_file(tmp_path / "missing" / "out.prom", reg)


def test_registry_factories_idempotent():
    reg = metrics.Registry()
    a = reg.counter("same_total", "S.")
    b = reg.counter("same_total", "S.")
    assert a is b
    a.inc()
    assert b.get() == 1


# ------------------------------------------------------------ event log
@pytest.mark.usefixtures("tmp_state_dir")
def test_events_roundtrip_and_filtering():
    events.emit("job", "7", "RUNNING")
    events.emit("job", "7", "SUCCEEDED")
    events.emit("replica", "svc/1", "READY", is_spot=True)
    jobs = events.read(kind="job", name="7")
    assert [r["event"] for r in jobs] == ["RUNNING", "SUCCEEDED"]
    rep = events.last("replica")
    assert rep["event"] == "READY" and rep["is_spot"] is True
    # Every record carries wall + monotonic stamps and the run id.
    for rec in jobs:
        assert rec["ts"] > 0 and rec["mono"] > 0
        assert rec["run_id"] == events.run_id()


@pytest.mark.usefixtures("tmp_state_dir")
def test_events_run_id_propagates_via_env(monkeypatch):
    monkeypatch.setenv(events.RUN_ID_ENV, "fixedrunid123")
    events.emit("cluster", "c", "UP")
    assert events.last("cluster")["run_id"] == "fixedrunid123"


@pytest.mark.usefixtures("tmp_state_dir")
def test_events_skip_garbage_lines():
    events.emit("job", "1", "RUNNING")
    with open(events.log_path(), "a") as f:
        f.write("{truncated json\n[1,2]\n")
    events.emit("job", "1", "SUCCEEDED")
    assert [r["event"] for r in events.read(kind="job")] == \
        ["RUNNING", "SUCCEEDED"]


@pytest.mark.usefixtures("tmp_state_dir")
def test_events_read_limit_and_tail():
    for i in range(10):
        events.emit("job", "1", f"E{i}")
    assert events.read(kind="job", limit=0) == []
    assert [r["event"] for r in events.read(kind="job", limit=3)] == \
        ["E7", "E8", "E9"]
    # Bounded tail read skips the head of the file but keeps whole
    # records (the partial first line is dropped, never mis-parsed).
    tail = events.read(kind="job", limit=None, max_bytes=200)
    assert 0 < len(tail) < 10
    assert tail[-1]["event"] == "E9"


@pytest.mark.usefixtures("tmp_state_dir")
def test_events_disabled_by_env(monkeypatch):
    monkeypatch.setenv(events.DISABLE_ENV, "1")
    events.emit("job", "1", "RUNNING")
    assert events.read() == []


@pytest.mark.usefixtures("tmp_state_dir")
def test_events_rotation(monkeypatch):
    """Rotation contract (jsonl_log.rotate_if_needed, shared with the
    trace sink): nothing rotates below the size threshold; crossing it
    moves the log to exactly ONE `.1` generation (no .2 ever);
    emission continues into a fresh current file; read() still sees
    both generations."""
    import pathlib
    monkeypatch.setattr(events, "_MAX_BYTES", 512)
    path = pathlib.Path(events.log_path())
    rotated = pathlib.Path(str(path) + ".1")

    events.emit("job", "1", "BEFORE")
    assert path.stat().st_size < 512 and not rotated.exists()

    # Pad the current generation over the threshold; the NEXT emit
    # must rotate first, then land in a fresh file.
    with open(path, "a") as f:
        f.write(" " * 512 + "\n")
    events.emit("job", "1", "AFTER")
    assert rotated.exists()
    assert not pathlib.Path(str(path) + ".2").exists()
    assert path.stat().st_size < 512          # fresh generation
    assert "BEFORE" in rotated.read_text()    # old records moved
    assert "AFTER" in path.read_text()

    # Emission keeps working, and a second rotation still leaves
    # exactly one retained generation (the old .1 is overwritten).
    with open(path, "a") as f:
        f.write(" " * 512 + "\n")
    events.emit("job", "1", "THIRD")
    assert not pathlib.Path(str(path) + ".2").exists()
    assert "AFTER" in rotated.read_text()
    # read() spans the rotation boundary (garbage padding skipped).
    assert [r["event"] for r in events.read(kind="job")] == \
        ["AFTER", "THIRD"]


@pytest.mark.usefixtures("tmp_state_dir")
def test_events_since_filter():
    events.emit("job", "1", "OLD")
    cut = time.time()
    time.sleep(0.02)
    events.emit("job", "1", "NEW")
    assert [r["event"] for r in events.read(kind="job", since=cut)] \
        == ["NEW"]
    assert [r["event"] for r in events.read(kind="job")] == \
        ["OLD", "NEW"]
    assert events.read(kind="job", since=time.time() + 60) == []


def test_parse_since_grammar():
    now = time.time()
    assert abs(events.parse_since("5m") - (now - 300)) < 2
    assert abs(events.parse_since("2h") - (now - 7200)) < 2
    assert abs(events.parse_since("30s") - (now - 30)) < 2
    assert abs(events.parse_since("1d") - (now - 86400)) < 2
    assert events.parse_since("1700000000") == 1700000000.0
    ts = events.parse_since("2026-08-04 12:30:00")
    assert time.localtime(ts)[:5] == (2026, 8, 4, 12, 30)
    assert events.parse_since("2026-08-04T12:30") == ts
    with pytest.raises(ValueError):
        events.parse_since("fortnight")


@pytest.mark.usefixtures("tmp_state_dir")
def test_cli_status_events_since():
    from skypilot_tpu import cli as cli_mod
    runner = CliRunner()
    events.emit("job", "9", "ANCIENT")
    # Rewrite the record's wall stamp 2h into the past: parse_since
    # math is tested above; here we pin the CLI plumbing end to end.
    import pathlib
    path = pathlib.Path(events.log_path())
    rec = json.loads(path.read_text())
    rec["ts"] -= 7200
    path.write_text(json.dumps(rec) + "\n")
    events.emit("job", "9", "FRESH")

    result = runner.invoke(cli_mod.cli,
                           ["status", "--events", "--since", "1h"])
    assert result.exit_code == 0, result.output
    assert "FRESH" in result.output and "ANCIENT" not in result.output
    result = runner.invoke(cli_mod.cli,
                           ["status", "--events", "--since", "3h"])
    assert result.exit_code == 0, result.output
    assert "FRESH" in result.output and "ANCIENT" in result.output
    # --since needs --events; junk values are UsageErrors, not stacks.
    result = runner.invoke(cli_mod.cli, ["status", "--since", "1h"])
    assert result.exit_code != 0
    assert "--since requires --events" in result.output
    result = runner.invoke(
        cli_mod.cli, ["status", "--events", "--since", "junk"])
    assert result.exit_code != 0
    assert "unparseable" in result.output


# -------------------------------------------- autoscaler decision history
def test_autoscaler_decision_history_and_event():
    """Pure-logic contract: plan() records history and QUEUES the scale
    event; the controller pops and writes it (the module itself does no
    file I/O, so unit tests never touch a real event log)."""
    from skypilot_tpu.serve import autoscalers
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    spec = SkyServiceSpec(min_replicas=1, max_replicas=5,
                          target_qps_per_replica=1.0,
                          qps_window_seconds=10,
                          upscale_delay_seconds=5,
                          downscale_delay_seconds=20)
    a = autoscalers.Autoscaler.from_spec(spec, service_name="svc-hist")
    t0 = 1000.0
    a.collect_request_information([t0 - 10 + k / 3.0 for k in range(48)])
    a.plan(now=t0, num_ready=1)
    assert a.pop_scale_event() is None     # hysteresis: no action yet
    a.plan(now=t0 + 6, num_ready=1)        # upscale fires here
    assert len(a.decision_history) == 2
    ts, qps, target, ready = a.decision_history[-1]
    assert target == 3 and qps > 0 and ready == 1
    scale = a.pop_scale_event()
    assert scale["event"] == "scale_up"
    assert scale["previous"] == 1 and scale["target"] == 3
    assert a.pop_scale_event() is None     # consumed exactly once
    # History survives a rolling-update autoscaler swap.
    new = autoscalers.Autoscaler.from_spec(spec,
                                           service_name="svc-hist")
    new.adopt_state(a)
    assert list(new.decision_history) == list(a.decision_history)


# ------------------------------------------------------------- timeline
def test_timeline_duration_survives_clock_step(tmp_path, monkeypatch):
    from skypilot_tpu.utils import timeline
    monkeypatch.setenv("STPU_TIMELINE_FILE", str(tmp_path / "t.json"))
    real_time = time.time
    # Wall clock steps BACKWARD 1h mid-block (NTP correction).
    monkeypatch.setattr(timeline.time, "time",
                        lambda: real_time() - 3600)
    with timeline.Event("stepped"):
        pass
    monkeypatch.undo()
    with timeline._lock:
        event = next(e for e in timeline._events
                     if e["name"] == "stepped")
    assert event["dur"] >= 0


# ------------------------------------------------------------------ CLI
def test_cli_metrics_and_events(tmp_state_dir):
    runner = CliRunner()
    # Local registry render: seed one metric in-process.
    metrics.counter("stpu_cli_probe_total", "Probe.").inc()
    result = runner.invoke(__import__("skypilot_tpu.cli",
                                      fromlist=["cli"]).cli,
                           ["metrics"])
    assert result.exit_code == 0, result.output
    assert "stpu_cli_probe_total 1" in result.output
    # Event log render.
    events.emit("job", "42", "RUNNING")
    from skypilot_tpu import cli as cli_mod
    result = runner.invoke(cli_mod.cli, ["status", "--events"])
    assert result.exit_code == 0, result.output
    assert "RUNNING" in result.output and "job" in result.output


# ------------------------------------------------------------- LB e2e
@pytest.fixture
def fast_tick(monkeypatch):
    monkeypatch.setenv("STPU_SERVE_TICK_SECONDS", "0.3")
    monkeypatch.setenv("STPU_LB_SYNC_SECONDS", "0.2")


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def _metric_value(text: str, prefix: str) -> float:
    """Sum all samples whose name+labels start with ``prefix``."""
    total = 0.0
    for line in text.splitlines():
        if line.startswith(prefix) and not line.startswith("#"):
            total += float(line.rsplit(" ", 1)[1])
    return total


@pytest.mark.usefixtures("tmp_state_dir", "fast_tick")
def test_lb_metrics_end_to_end():
    """`curl $LB/metrics` after proxied requests: request histogram
    counts match the request count, and the controller's autoscaler /
    replica-state metrics ride the sync into the same exposition."""
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.serve.service_spec import SkyServiceSpec
    from skypilot_tpu.task import Task
    from skypilot_tpu.resources import Resources

    task = Task("metrics-svc", run=(
        'cd $(mktemp -d) && echo "hello" > index.html && '
        'exec python3 -m http.server $SKYPILOT_SERVE_REPLICA_PORT'))
    task.set_resources(Resources(cloud="local"))
    task.service = SkyServiceSpec(readiness_path="/",
                                  initial_delay_seconds=60,
                                  min_replicas=1)
    name, endpoint = serve_core.up(task, "svc-metrics",
                                   controller="local")
    try:
        serve_core.wait_ready(name, timeout=90)
        n_requests = 5
        for _ in range(n_requests):
            status, body = _get(endpoint + "/")
            assert status == 200 and "hello" in body

        # The LB observes each request synchronously after the last
        # byte; the controller snapshot arrives on the next /sync.
        # Poll briefly for both.
        deadline = time.time() + 20
        text = ""
        while time.time() < deadline:
            status, text = _get(endpoint + "/metrics")
            assert status == 200
            if (_metric_value(text, "stpu_lb_requests_total")
                    >= n_requests and "stpu_serve_replicas" in text):
                break
            time.sleep(0.3)

        # Request counter and latency histogram agree with the traffic.
        assert _metric_value(
            text, 'stpu_lb_requests_total{method="GET",code="200"}'
        ) == n_requests
        assert _metric_value(
            text, "stpu_lb_request_duration_seconds_count") == \
            n_requests
        assert _metric_value(
            text, "stpu_lb_request_duration_seconds_bucket"
            '{code="200",le="+Inf"}') == n_requests
        assert _metric_value(text, "stpu_lb_streamed_bytes_count") == \
            n_requests
        # /metrics scrapes are NOT proxied requests.
        assert _metric_value(text, "stpu_lb_requests_total") == \
            n_requests

        # The merged document is VALID exposition: one HELP/TYPE block
        # per family, even though the controller process registers the
        # LB families too (it imports the LB module).
        help_names = [line.split()[2] for line in text.splitlines()
                      if line.startswith("# HELP ")]
        assert len(help_names) == len(set(help_names)), help_names

        # Controller-process metrics ride the /sync snapshot:
        # replica-state gauges and autoscaler decision counters.
        assert 'stpu_serve_replicas{service="svc-metrics",' \
            'state="READY"} 1' in text
        assert "stpu_autoscaler_decisions_total" in text
        assert 'stpu_autoscaler_target_replicas{service="svc-metrics"}'\
            in text

        # The same exposition is reachable through `stpu metrics --url`.
        from skypilot_tpu import core as sdk_core
        scraped = sdk_core.metrics_snapshot(endpoint)
        assert "stpu_lb_requests_total" in scraped
    finally:
        serve_core.down([name], timeout=60)
