"""Opt-in REAL-cloud smoke tests: ``pytest --gcp-live tests/test_smoke_live.py``.

Reference analog: tests/test_smoke.py (the reference's 5,308-line
real-cloud suite, gated by conftest --gcp/--tpu flags). This is the
runnable checklist for the day someone points the GCP provisioner at a
real project: launch -> run -> queue -> autostop --down -> gone, against
a real v5e single-host slice (the cheapest TPU the catalog offers).

Never runs in CI: collection skips everything without --gcp-live, and
even with the flag each test re-checks credentials and SKIPS (not
fails) when gcloud/project/quota are absent. COSTS REAL MONEY when it
runs; every cluster is created with a finally-teardown.
"""
import time
import uuid

import pytest

pytestmark = pytest.mark.gcp_live

_ACCELERATOR = "tpu-v5e-8"  # single host: cheapest real slice


def _require_gcp():
    from skypilot_tpu import clouds as clouds_lib
    ok, reason = clouds_lib.get_cloud("gcp").check_credentials()
    if not ok:
        pytest.skip(f"no usable GCP credentials: {reason}")


@pytest.mark.timeout(1800)
def test_launch_run_autostop_down_live():
    _require_gcp()
    from skypilot_tpu import core, execution
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.status_lib import ClusterStatus
    from skypilot_tpu.task import Task

    name = f"stpu-smoke-{uuid.uuid4().hex[:6]}"
    task = Task("smoke", run="python3 -c 'import socket; "
                             "print(\"live-ok\", socket.gethostname())'")
    task.set_resources(Resources(cloud="gcp",
                                 accelerator=_ACCELERATOR))
    try:
        job_id, handle = execution.launch(
            task, cluster_name=name, detach_run=True, stream_logs=False,
            retry_until_up=False)
        assert handle is not None

        # The head-resident queue answers over SSH.
        deadline = time.time() + 300
        status = None
        while time.time() < deadline:
            status = core.job_status(name, [job_id])[job_id]
            if status in ("SUCCEEDED", "FAILED", "FAILED_SETUP"):
                break
            time.sleep(10)
        assert status == "SUCCEEDED", f"job ended {status}"
        assert core.tail_logs(name, job_id, follow=False) == 0

        # Autostop --down: the on-host daemon terminates the idle slice
        # with zero further client involvement.
        core.autostop(name, 0, down_after=True)
        deadline = time.time() + 900
        while time.time() < deadline:
            records = core.status([name], refresh=True)
            if not records or records[0]["status"] is None:
                return  # daemon tore it down
            if records[0]["status"] == ClusterStatus.STOPPED:
                break
            time.sleep(30)
        records = core.status([name], refresh=True)
        assert not records, "cluster still alive after autostop --down"
    finally:
        try:
            core.down(name, purge=True)
        except Exception:  # noqa: BLE001 — already gone
            pass
