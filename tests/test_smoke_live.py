"""Opt-in REAL-cloud smoke tests: ``pytest --gcp-live tests/test_smoke_live.py``.

Reference analog: tests/test_smoke.py (the reference's 5,308-line
real-cloud suite, gated by conftest --gcp/--tpu flags). This is the
runnable checklist for the day someone points the GCP provisioner at a
real project: launch -> run -> queue -> autostop --down -> gone, against
a real v5e single-host slice (the cheapest TPU the catalog offers).

Never runs in CI: collection skips everything without --gcp-live, and
even with the flag each test re-checks credentials and SKIPS (not
fails) when gcloud/project/quota are absent. COSTS REAL MONEY when it
runs; every cluster is created with a finally-teardown.
"""
import time
import uuid

import pytest

pytestmark = pytest.mark.gcp_live

_ACCELERATOR = "tpu-v5e-8"  # single host: cheapest real slice


def _require_gcp():
    from skypilot_tpu import clouds as clouds_lib
    ok, reason = clouds_lib.get_cloud("gcp").check_credentials()
    if not ok:
        pytest.skip(f"no usable GCP credentials: {reason}")


@pytest.mark.timeout(1800)
def test_launch_run_autostop_down_live():
    _require_gcp()
    from skypilot_tpu import core, execution
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.status_lib import ClusterStatus
    from skypilot_tpu.task import Task

    name = f"stpu-smoke-{uuid.uuid4().hex[:6]}"
    task = Task("smoke", run="python3 -c 'import socket; "
                             "print(\"live-ok\", socket.gethostname())'")
    task.set_resources(Resources(cloud="gcp",
                                 accelerator=_ACCELERATOR))
    try:
        job_id, handle = execution.launch(
            task, cluster_name=name, detach_run=True, stream_logs=False,
            retry_until_up=False)
        assert handle is not None

        # The head-resident queue answers over SSH.
        deadline = time.time() + 300
        status = None
        while time.time() < deadline:
            status = core.job_status(name, [job_id])[job_id]
            if status in ("SUCCEEDED", "FAILED", "FAILED_SETUP"):
                break
            time.sleep(10)
        assert status == "SUCCEEDED", f"job ended {status}"
        assert core.tail_logs(name, job_id, follow=False) == 0

        # Autostop --down: the on-host daemon terminates the idle slice
        # with zero further client involvement.
        core.autostop(name, 0, down_after=True)
        deadline = time.time() + 900
        while time.time() < deadline:
            records = core.status([name], refresh=True)
            if not records or records[0]["status"] is None:
                return  # daemon tore it down
            if records[0]["status"] == ClusterStatus.STOPPED:
                break
            time.sleep(30)
        records = core.status([name], refresh=True)
        assert not records, "cluster still alive after autostop --down"
    finally:
        try:
            core.down(name, purge=True)
        except Exception:  # noqa: BLE001 — already gone
            pass


@pytest.mark.timeout(1800)
def test_ports_firewall_live():
    """Launch with resources.ports on real GCP: the per-cluster
    firewall rule exists while the cluster is up, an HTTP server on the
    opened port answers from THIS machine (outside the VPC), and the
    rule is deleted on down (VERDICT r4 #1 done-bar, live leg)."""
    _require_gcp()
    import urllib.request

    from skypilot_tpu import core, execution
    from skypilot_tpu.provision import gcp as gcp_provision
    from skypilot_tpu.resources import Resources
    from skypilot_tpu.task import Task

    name = f"stpu-ports-{uuid.uuid4().hex[:6]}"
    project = None
    task = Task("ports-smoke", run=(
        "nohup python3 -m http.server 8080 >/dev/null 2>&1 & "
        "sleep 2 && echo serving"))
    task.set_resources(Resources(cloud="gcp", accelerator=_ACCELERATOR,
                                 ports=("8080",)))
    try:
        _, handle = execution.launch(task, cluster_name=name,
                                     detach_run=True, stream_logs=False)
        project = gcp_provision._project_of(
            handle.cluster_info.provider_config)
        rule = gcp_provision.compute_rest(
            "GET", f"projects/{project}/global/firewalls/"
                   f"{gcp_provision._firewall_rule_name(name)}")
        assert rule["targetTags"] == [gcp_provision._network_tag(name)]
        head = handle.cluster_info.get_head_instance()
        deadline = time.time() + 120
        reachable = False
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://{head.external_ip}:8080/",
                        timeout=5) as resp:
                    reachable = resp.status == 200
                    break
            except Exception:  # noqa: BLE001 — server still starting
                time.sleep(3)
        assert reachable, "opened port not reachable from outside"
    finally:
        try:
            core.down(name, purge=True)
        except Exception:  # noqa: BLE001 — cluster may not exist
            pass
    # Rule cleaned up with the cluster — checked in the SAME project
    # the rule was created in (the gcloud default may differ).
    assert project is not None, "launch never resolved a project"
    with pytest.raises(gcp_provision.GcpApiError) as err:
        gcp_provision.compute_rest(
            "GET", f"projects/{project}/global/firewalls/"
                   f"{gcp_provision._firewall_rule_name(name)}")
    assert err.value.status == 404
