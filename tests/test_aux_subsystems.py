"""Aux-subsystem gaps: general-DAG optimizer, accelerator registry,
jobs dashboard, usage telemetry."""
import json
import threading
import urllib.request

import pytest

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import optimizer as optimizer_lib
from skypilot_tpu.optimizer import OptimizeTarget, Optimizer
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.utils import accelerator_registry


def _tpu_task(name, acc="tpu-v5e-8", out_gb=0.0):
    t = Task(name, run="true")
    t.set_resources(Resources(accelerator=acc))
    if out_gb:
        t.estimated_output_gb = out_gb
    return t


@pytest.mark.usefixtures("tmp_state_dir")
def test_general_dag_matches_chain_dp():
    """A chain routed through the general-DAG solver must match the
    chain DP exactly (the reference cross-checks DP vs ILP the same
    way, tests/test_optimizer_random_dag.py)."""
    for minimize in (OptimizeTarget.COST, OptimizeTarget.TIME):
        tasks_a = [_tpu_task(f"a{i}", out_gb=50.0) for i in range(3)]
        with dag_lib.Dag() as chain:
            for t in tasks_a:
                chain.add(t)
            chain.add_edge(tasks_a[0], tasks_a[1])
            chain.add_edge(tasks_a[1], tasks_a[2])
        assert chain.is_chain()
        Optimizer.optimize(chain, minimize=minimize, quiet=True)

        per_task = {id(t): optimizer_lib.launchable_candidates(t)
                    for t in chain.topo_order()}
        general = Optimizer._optimize_general(
            chain, chain.topo_order(), per_task, minimize)
        for t in tasks_a:
            assert general[id(t)].resources.zone == \
                t.best_resources.zone, minimize


@pytest.mark.usefixtures("tmp_state_dir")
def test_general_dag_egress_aware():
    """Diamond DAG: the solver must co-locate tasks to avoid egress."""
    a = _tpu_task("a", out_gb=1000.0)
    b = _tpu_task("b", out_gb=1000.0)
    c = _tpu_task("c", out_gb=1000.0)
    d = _tpu_task("d")
    with dag_lib.Dag() as dag:
        for t in (a, b, c, d):
            dag.add(t)
        dag.add_edge(a, b)
        dag.add_edge(a, c)
        dag.add_edge(b, d)
        dag.add_edge(c, d)
    assert not dag.is_chain()
    Optimizer.optimize(dag, quiet=True)
    regions = {t.best_resources.region for t in (a, b, c, d)}
    assert len(regions) == 1, f"egress not avoided: {regions}"


def test_accelerator_canonicalization():
    can = accelerator_registry.canonicalize_accelerator_name
    assert can("tpu-v5e-8") == "tpu-v5e-8"
    assert can("V5E-8") == "tpu-v5e-8"
    assert can("tpu_v5p_64") == "tpu-v5p-64"
    assert can("v5litepod-8") == "tpu-v5e-8"
    with pytest.raises(exceptions.InvalidTaskError, match="Did you mean"):
        can("tpu-v5e-9")
    assert accelerator_registry.is_schedulable_non_gpu_accelerator(
        "tpu-v5e-8")
    assert not accelerator_registry.is_schedulable_non_gpu_accelerator(
        "A100")
    # Resources normalizes on construction.
    assert Resources(accelerator="V5E-8").accelerator == "tpu-v5e-8"


@pytest.mark.usefixtures("tmp_state_dir")
def test_jobs_dashboard_serves_queue():
    from skypilot_tpu.jobs import dashboard
    from skypilot_tpu.jobs import state as jobs_state
    from skypilot_tpu.jobs.state import ManagedJobStatus

    job_id = jobs_state.add_job("dash-job", "/dev/null", "local", 1)
    jobs_state.set_status(job_id, ManagedJobStatus.RUNNING)

    httpd = dashboard.serve(0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read().decode()
        assert "dash-job" in page and "RUNNING" in page
        api = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api", timeout=10).read())
        assert api["jobs"][0]["job_name"] == "dash-job"
    finally:
        httpd.shutdown()


@pytest.mark.usefixtures("tmp_state_dir")
def test_usage_records_entrypoints(monkeypatch):
    from skypilot_tpu.utils import paths, usage_lib

    @usage_lib.entrypoint
    def sample(x):
        if x < 0:
            raise ValueError("nope")
        return x * 2

    assert sample(3) == 6
    with pytest.raises(ValueError):
        sample(-1)
    lines = [json.loads(line) for line in
             (paths.home() / "usage" / "usage.jsonl"
              ).read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["outcome"] == "ok"
    assert lines[1]["outcome"] == "error"
    assert lines[1]["exception"] == "ValueError"
    assert "sample" in lines[0]["entrypoint"]

    # Opt-out: nothing new recorded.
    monkeypatch.setenv(usage_lib.DISABLE_ENV, "1")
    sample(1)
    lines2 = (paths.home() / "usage" / "usage.jsonl"
              ).read_text().splitlines()
    assert len(lines2) == 2


@pytest.mark.usefixtures("tmp_state_dir")
def test_owner_identity_check(monkeypatch):
    from skypilot_tpu import core, execution, global_user_state
    from skypilot_tpu.utils import usage_lib

    t = Task("own", run="true")
    t.set_resources(Resources(cloud="local"))
    execution.launch(t, cluster_name="t-own", detach_run=True,
                     stream_logs=False)
    record = global_user_state.get_cluster_from_name("t-own")
    assert record["owner"] == usage_lib.user_identity()
    core.queue("t-own")  # same identity: fine

    monkeypatch.setattr(usage_lib, "user_identity", lambda: "someone")
    with pytest.raises(
            exceptions.ClusterOwnerIdentityMismatchError,
            match="created by identity"):
        core.stop("t-own")
    # Override for intentional handover.
    monkeypatch.setenv("STPU_SKIP_IDENTITY_CHECK", "1")
    core.down("t-own")
    assert global_user_state.get_cluster_from_name("t-own") is None


@pytest.mark.usefixtures("tmp_state_dir")
def test_ssh_config_helper(tmp_path, monkeypatch):
    from skypilot_tpu.provision.common import ClusterInfo, InstanceInfo
    from skypilot_tpu.utils import ssh_config

    user_cfg = tmp_path / "sshconfig"
    monkeypatch.setenv("STPU_SSH_CONFIG", str(user_cfg))

    instances = {
        f"h{i}": InstanceInfo(
            instance_id=f"h{i}", internal_ip=f"10.0.0.{i}",
            external_ip=f"34.1.2.{i}", slice_id="slice-0",
            host_index=i, tags={})
        for i in range(2)
    }
    info = ClusterInfo(cluster_name="c1", provider_name="gcp",
                       region="us-central1", zone="us-central1-a",
                       instances=instances, head_instance_id="h0",
                       provider_config={})

    class FakeHandle:
        cluster_name = "c1"
        cluster_info = info

    ssh_config.add_cluster(FakeHandle())
    block = ssh_config.cluster_config_path("c1").read_text()
    assert "Host c1\n" in block and "HostName 34.1.2.0" in block
    assert "Host c1-1\n" in block and "HostName 34.1.2.1" in block
    # Include line prepended exactly once, idempotently.
    ssh_config.add_cluster(FakeHandle())
    assert user_cfg.read_text().count("Include") == 1

    ssh_config.remove_cluster("c1")
    assert ssh_config.cluster_config_path("c1") is None
    ssh_config.remove_cluster("c1")  # idempotent


def test_device_profile_writes_trace(tmp_path, monkeypatch):
    """device_profile captures an XLA trace when armed, no-ops when not
    (SURVEY §5: the on-device profiler the reference lacks)."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu import callbacks

    # Unarmed: a null context, zero side effects.
    monkeypatch.delenv("STPU_PROFILE_DIR", raising=False)
    with callbacks.device_profile():
        pass

    prof_dir = tmp_path / "prof"
    with callbacks.device_profile(log_dir=str(prof_dir)):
        jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
    traces = list(prof_dir.rglob("*.xplane.pb"))
    assert traces, f"no xplane trace under {prof_dir}"


@pytest.mark.usefixtures("tmp_state_dir")
def test_usage_remote_sink(monkeypatch):
    """Opt-in remote sink (VERDICT r3 missing #6; reference:
    usage_lib._send_to_loki): plain-JSON endpoint and Loki push shape,
    best-effort, and the opt-out env wins over any configured sink."""
    import http.server
    import json as json_lib
    import socketserver
    import threading
    import time as time_lib

    from skypilot_tpu import config as config_lib
    from skypilot_tpu.utils import usage_lib

    received = []

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            received.append((self.path,
                             json_lib.loads(self.rfile.read(n))))
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()

    class Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    srv = Srv(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    port = srv.server_address[1]
    try:
        # Plain endpoint.
        monkeypatch.setattr(
            config_lib, "get_nested",
            lambda keys, default=None:
                f"http://127.0.0.1:{port}/usage"
                if keys == ("usage", "endpoint") else None)

        @usage_lib.entrypoint
        def op():
            return 42

        assert op() == 42
        deadline = time_lib.time() + 5
        while not received and time_lib.time() < deadline:
            time_lib.sleep(0.05)
        assert received and received[0][0] == "/usage"
        assert received[0][1]["entrypoint"].endswith("op")

        # Loki shape.
        received.clear()
        monkeypatch.setattr(
            config_lib, "get_nested",
            lambda keys, default=None:
                f"http://127.0.0.1:{port}/loki/api/v1/push"
                if keys == ("usage", "loki_url") else None)
        assert op() == 42
        deadline = time_lib.time() + 5
        while not received and time_lib.time() < deadline:
            time_lib.sleep(0.05)
        path, body = received[0]
        assert path == "/loki/api/v1/push"
        stream = body["streams"][0]
        assert stream["stream"]["source"] == "skypilot_tpu"
        inner = json_lib.loads(stream["values"][0][1])
        assert inner["outcome"] == "ok"

        # Opt-out env beats the sink.
        received.clear()
        monkeypatch.setenv(usage_lib.DISABLE_ENV, "1")
        assert op() == 42
        time_lib.sleep(0.3)
        assert received == []
    finally:
        srv.shutdown()


def test_config_schema_accepts_all_read_keys(tmp_path, monkeypatch):
    """Every config key the code READS must be schema-legal — the
    kubernetes/azure/controller/usage sections were read by
    slice_backend, AzureBlobStore, controller_utils and usage_lib but
    rejected by CONFIG_SCHEMA's additionalProperties: a configured user
    crashed at config load."""
    from skypilot_tpu.utils import schemas
    schemas.validate_config({
        "kubernetes": {"namespace": "ml",
                       "gke_accelerator_type": "tpu-v5-lite-podslice",
                       "gke_tpu_topology": "2x4"},
        "azure": {"storage_account": "acct"},
        "controller": {"bucket_store": "gcs"},
        "usage": {"endpoint": "http://collector/u",
                  "loki_url": "http://loki/loki/api/v1/push"},
        "serve": {"controller": {"mode": "local"}},
        "jobs": {"controller": {"mode": "local"}},
        "gcp": {"project_id": "p"},
    })
    import pytest as _pytest
    from skypilot_tpu import exceptions as exc
    with _pytest.raises(exc.InvalidTaskError):
        schemas.validate_config({"nonsense": {}})


@pytest.mark.usefixtures("tmp_state_dir")
def test_usage_survives_malformed_config(monkeypatch):
    """Telemetry must never break the call — including when reading the
    sink config itself blows up (malformed config.yaml)."""
    from skypilot_tpu.utils import paths, usage_lib
    (paths.home()).mkdir(parents=True, exist_ok=True)
    (paths.home() / "config.yaml").write_text("usage: [not, a, dict\n")

    @usage_lib.entrypoint
    def op():
        return "fine"

    assert op() == "fine"


def test_proc_utils_cmdline_matches():
    """Recorded pids are verified against /proc cmdline before any
    SIGTERM (recycled-pid protection; advisor r4 serve/service.py)."""
    import os
    from skypilot_tpu.utils import proc_utils
    # Our own process is a python invocation.
    assert proc_utils.cmdline_matches(os.getpid(), "python")
    assert not proc_utils.cmdline_matches(os.getpid(),
                                          "definitely-not-in-argv")
    # A pid that cannot exist: must be False, not an exception.
    assert not proc_utils.cmdline_matches(2 ** 22 + 12345, "python")


def test_usage_remote_sink_bounded(monkeypatch):
    """In-flight remote sends are bounded: past the cap new sends are
    dropped, not threaded (advisor r4 usage_lib finding)."""
    import threading
    from skypilot_tpu.utils import usage_lib

    release = threading.Event()
    started = []
    _RealThread = threading.Thread  # usage_lib.threading IS this module

    class _FakeThread:
        def __init__(self, target=None, daemon=None):
            self._t = _RealThread(target=target, daemon=True)

        def start(self):
            started.append(self)
            self._t.start()

        def is_alive(self):
            return self._t.is_alive()

        def join(self, timeout=None):
            self._t.join(timeout)

    monkeypatch.setattr(usage_lib.threading, "Thread", _FakeThread)
    monkeypatch.setattr(usage_lib, "_pending_sends", [])

    def slow_post(url, data=None, headers=None):
        raise AssertionError("unused")

    # Patch the config read + make the POST hang until released.
    from skypilot_tpu import config as config_lib
    monkeypatch.setattr(config_lib, "get_nested",
                        lambda keys, default=None:
                        "http://127.0.0.1:1/sink"
                        if keys == ("usage", "endpoint") else None)
    import urllib.request as _ur

    def hanging_urlopen(req, timeout=None):
        release.wait(10)
        raise OSError("sink down")

    monkeypatch.setattr(_ur, "urlopen", hanging_urlopen)
    try:
        for _ in range(usage_lib._MAX_INFLIGHT_SENDS + 5):
            usage_lib._maybe_send_remote({"ts": 0.0, "op": "x"})
        assert len(started) == usage_lib._MAX_INFLIGHT_SENDS
    finally:
        release.set()
