"""Serve end-to-end on the local provider: real replicas (HTTP servers in
local-provider clusters), real LB proxying, replica replacement after a
kill, clean teardown.

Reference analog: tests/skyserve/ smoke fixtures — but hermetic.
"""
import time
import urllib.error
import urllib.request

import pytest

from skypilot_tpu import global_user_state
from skypilot_tpu.serve import core as serve_core
from skypilot_tpu.serve import serve_state
from skypilot_tpu.serve.serve_state import ReplicaStatus, ServiceStatus
from skypilot_tpu.serve.service_spec import SkyServiceSpec
from skypilot_tpu.task import Task
from skypilot_tpu.resources import Resources


@pytest.fixture(autouse=True)
def fast_tick(monkeypatch):
    monkeypatch.setenv("STPU_SERVE_TICK_SECONDS", "0.3")
    monkeypatch.setenv("STPU_LB_SYNC_SECONDS", "0.2")


def _server_task(replicas=2):
    task = Task("hello-svc", run=(
        'cd $(mktemp -d) && echo "port-$SKYPILOT_SERVE_REPLICA_PORT" '
        '> index.html && '
        'exec python3 -m http.server $SKYPILOT_SERVE_REPLICA_PORT'))
    task.set_resources(Resources(cloud="local"))
    task.service = SkyServiceSpec(readiness_path="/",
                                  initial_delay_seconds=60,
                                  min_replicas=replicas)
    return task


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


@pytest.mark.usefixtures("tmp_state_dir")
def test_serve_up_scale_replace_down():
    name, endpoint = serve_core.up(_server_task(replicas=2), "svc-e2e",
                                    controller="local")
    try:
        got = serve_core.wait_ready(name, timeout=90)
        assert got == endpoint

        # Both replicas become READY and the LB round-robins across them.
        deadline = time.time() + 60
        while time.time() < deadline:
            reps = serve_state.get_replicas(name)
            if sum(1 for r in reps
                   if r["status"] == ReplicaStatus.READY) == 2:
                break
            time.sleep(0.3)
        bodies = set()
        for _ in range(6):
            status, body = _get(endpoint + "/")
            assert status == 200
            bodies.add(body.strip())
        assert len(bodies) == 2, f"expected both replicas hit: {bodies}"

        # Kill replica 1's cluster out from under the controller: probes
        # fail -> provider says dead -> PREEMPTED -> replacement launched.
        rep1 = serve_state.get_replicas(name)[0]
        record = global_user_state.get_cluster_from_name(
            rep1["cluster_name"])
        from skypilot_tpu.backends import slice_backend
        slice_backend.SliceBackend().teardown(record["handle"],
                                              terminate=True, purge=True)
        deadline = time.time() + 90
        replaced = False
        while time.time() < deadline:
            reps = serve_state.get_replicas(name)
            ids = {r["replica_id"] for r in reps}
            ready = [r for r in reps
                     if r["status"] == ReplicaStatus.READY]
            if rep1["replica_id"] not in ids and len(ready) == 2:
                replaced = True
                break
            time.sleep(0.3)
        assert replaced, f"replica not replaced: {reps}"
        # Service stayed/returned READY throughout recovery.
        assert serve_state.get_service(name)["status"] == \
            ServiceStatus.READY
    finally:
        serve_core.down([name], timeout=60)

    # Everything cleaned: service row gone, no replica clusters left.
    assert serve_state.get_service(name) is None
    leftovers = [r["name"] for r in global_user_state.get_clusters()
                 if r["name"].startswith("svc-e2e-replica")]
    assert leftovers == []


@pytest.mark.usefixtures("tmp_state_dir")
def test_serve_lb_503_before_ready():
    task = _server_task(replicas=1)
    # Slow server: nothing listens for a while.
    task.run = ("sleep 300")
    name, endpoint = serve_core.up(task, "svc-slow", controller="local")
    try:
        deadline = time.time() + 30
        got = None
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(endpoint + "/",
                                            timeout=3) as resp:
                    got = resp.status
                break
            except urllib.error.HTTPError as e:
                got = e.code
                break
            except (urllib.error.URLError, ConnectionError, OSError):
                time.sleep(0.3)  # LB not listening yet
        assert got == 503
    finally:
        serve_core.down([name], timeout=60)


@pytest.mark.usefixtures("tmp_state_dir")
def test_service_spec_yaml_roundtrip():
    spec = SkyServiceSpec.from_yaml_config({
        "readiness_probe": {"path": "/health",
                            "initial_delay_seconds": 42},
        "replica_policy": {"min_replicas": 2, "max_replicas": 6,
                           "target_qps_per_replica": 2.5},
    })
    assert spec.readiness_path == "/health"
    assert spec.initial_delay_seconds == 42
    assert spec.autoscaling_enabled
    spec2 = SkyServiceSpec.from_yaml_config(spec.to_yaml_config())
    assert spec2 == spec

    simple = SkyServiceSpec.from_yaml_config(
        {"readiness_probe": "/", "replicas": 3})
    assert simple.min_replicas == 3
    assert not simple.autoscaling_enabled


@pytest.mark.usefixtures("tmp_state_dir")
def test_serve_rolling_update():
    """`serve update` rolls replicas to a new task revision: new-version
    replicas come READY before outdated ones are drained, and the
    service keeps answering throughout."""
    def versioned_task(body):
        task = Task("roll-svc", run=(
            f'cd $(mktemp -d) && echo "{body}" > index.html && '
            'exec python3 -m http.server $SKYPILOT_SERVE_REPLICA_PORT'))
        task.set_resources(Resources(cloud="local"))
        task.service = SkyServiceSpec(readiness_path="/",
                                      initial_delay_seconds=60,
                                      min_replicas=2)
        return task

    name, endpoint = serve_core.up(versioned_task("body-v1"), "svc-roll",
                                   controller="local")
    try:
        serve_core.wait_ready(name, timeout=90)
        _, body = _get(endpoint + "/")
        assert "body-v1" in body

        version = serve_core.update(versioned_task("body-v2"), name,
                                    controller="local")
        assert version == 2

        # Roll completes: all replicas on v2, old ones gone, service
        # kept answering every poll along the way.
        deadline = time.time() + 120
        rolled = False
        while time.time() < deadline:
            status, body = _get(endpoint + "/")  # never a dropped req
            assert status == 200
            reps = serve_state.get_replicas(name)
            ready = [r for r in reps
                     if r["status"] == ReplicaStatus.READY]
            if (len(ready) == 2 and
                    all(r["version"] == 2 for r in ready) and
                    all(r["version"] == 2 for r in reps)):
                rolled = True
                break
            time.sleep(0.3)
        assert rolled, f"rollout incomplete: {serve_state.get_replicas(name)}"

        # Traffic now comes from v2 bodies only.
        bodies = {_get(endpoint + "/")[1].strip() for _ in range(4)}
        assert bodies == {"body-v2"}, bodies
    finally:
        serve_core.down([name], timeout=90)


@pytest.mark.usefixtures("tmp_state_dir")
def test_lb_survives_controller_crash():
    """Data-plane isolation: SIGKILL the controller process; the LB (its
    own process) keeps proxying the last-known replica set. serve down
    then cleans both up."""
    import os
    import signal as signal_lib

    task = _server_task(replicas=1)
    name, endpoint = serve_core.up(task, "crash-svc", controller="local")

    deadline = time.time() + 60
    while time.time() < deadline:
        svcs = serve_core.status(["crash-svc"])
        if svcs and any(r["status"] == "READY"
                        for r in svcs[0]["replicas"]):
            break
        time.sleep(0.3)
    else:
        pytest.fail(f"never READY: {svcs}")
    # Give the LB one sync so it holds the ready set.
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            status, _ = _get(endpoint, timeout=3)
            if status == 200:
                break
        except Exception:
            pass
        time.sleep(0.2)

    svc = serve_state.get_service("crash-svc")
    controller_pid, lb_pid = svc["controller_pid"], svc["lb_pid"]
    assert controller_pid and lb_pid and controller_pid != lb_pid
    os.kill(controller_pid, signal_lib.SIGKILL)  # crash, not clean stop
    time.sleep(1.0)

    # Control plane is dead; the data plane still serves.
    status, body = _get(endpoint, timeout=5)
    assert status == 200 and "port-" in body

    # Teardown finalizes the dead controller AND kills the LB process.
    serve_core.down(["crash-svc"], timeout=10)
    deadline = time.time() + 10
    lb_dead = False
    while time.time() < deadline:
        try:
            os.kill(lb_pid, 0)
            time.sleep(0.2)
        except ProcessLookupError:
            lb_dead = True
            break
    assert lb_dead, "LB process survived serve down"
    assert serve_state.get_service("crash-svc") is None


@pytest.mark.usefixtures("tmp_state_dir")
def test_spot_preemption_ondemand_fallback():
    """Spot serving with dynamic on-demand fallback (VERDICT r3 #1;
    reference: sky/serve/autoscalers.py:527-636): a spot replica is
    preempted -> the on-demand pool backfills the gap within a tick ->
    spot recovers -> the backfill is shed back to the base carve-out."""
    task = Task("spot-svc", run=(
        'cd $(mktemp -d) && echo "port-$SKYPILOT_SERVE_REPLICA_PORT" '
        '> index.html && '
        'exec python3 -m http.server $SKYPILOT_SERVE_REPLICA_PORT'))
    task.set_resources(Resources(cloud="local", use_spot=True))
    task.service = SkyServiceSpec(readiness_path="/",
                                  initial_delay_seconds=60,
                                  min_replicas=2,
                                  base_ondemand_fallback_replicas=1,
                                  dynamic_ondemand_fallback=True)
    name, endpoint = serve_core.up(task, "svc-spot", controller="local")
    try:
        serve_core.wait_ready(name, timeout=90)

        def pools():
            reps = serve_state.get_replicas(name)
            spot = [r for r in reps if r["is_spot"]]
            od = [r for r in reps if not r["is_spot"]]
            return reps, spot, od

        # Steady state: 1 spot + 1 on-demand (the base carve-out), READY.
        deadline = time.time() + 60
        while time.time() < deadline:
            reps, spot, od = pools()
            if (len(spot) == 1 and len(od) == 1 and all(
                    r["status"] == ReplicaStatus.READY for r in reps)):
                break
            time.sleep(0.3)
        assert len(spot) == 1 and len(od) == 1, f"pools wrong: {reps}"

        # Preempt the spot replica: tear its cluster down underneath.
        victim = spot[0]
        record = global_user_state.get_cluster_from_name(
            victim["cluster_name"])
        from skypilot_tpu.backends import slice_backend
        slice_backend.SliceBackend().teardown(record["handle"],
                                              terminate=True, purge=True)

        # Dynamic fallback: a SECOND on-demand replica appears while
        # spot capacity is down.
        deadline = time.time() + 90
        saw_backfill = False
        while time.time() < deadline:
            _, _, od = pools()
            if len(od) >= 2:
                saw_backfill = True
                break
            time.sleep(0.1)
        assert saw_backfill, "on-demand backfill never launched"

        # Spot recovers (replacement launched by the spot pool) and the
        # surplus on-demand replica is shed: back to 1 spot + 1 od READY.
        deadline = time.time() + 120
        settled = False
        while time.time() < deadline:
            reps, spot, od = pools()
            ready_spot = [r for r in spot
                          if r["status"] == ReplicaStatus.READY]
            ready_od = [r for r in od
                        if r["status"] == ReplicaStatus.READY]
            if (len(ready_spot) == 1 and len(spot) == 1 and
                    len(ready_od) == 1 and len(od) == 1):
                settled = True
                break
            time.sleep(0.3)
        assert settled, f"did not settle to 1 spot + 1 od: {reps}"
        # The surviving spot replica is a REPLACEMENT, not the victim.
        assert spot[0]["replica_id"] != victim["replica_id"]
    finally:
        serve_core.down([name], timeout=60)


@pytest.mark.usefixtures("tmp_state_dir")
def test_controller_restart_adopts_replicas():
    """Kill -9 the controller; a respawned controller ADOPTS the live
    replicas recorded in serve state instead of relaunching a second
    fleet (VERDICT r3 weak #7; reference:
    sky/serve/replica_managers.py:606 constructor recovery)."""
    import os
    import signal
    import subprocess
    import sys

    name, endpoint = serve_core.up(_server_task(replicas=2), "svc-adopt",
                                   controller="local")
    proc = None
    try:
        serve_core.wait_ready(name, timeout=90)
        deadline = time.time() + 60
        while time.time() < deadline:
            reps = serve_state.get_replicas(name)
            if sum(1 for r in reps
                   if r["status"] == ReplicaStatus.READY) == 2:
                break
            time.sleep(0.3)
        before = {r["replica_id"]: r["cluster_name"] for r in reps}
        clusters_before = sorted(
            r["name"] for r in global_user_state.get_clusters())
        svc = serve_state.get_service(name)

        os.kill(svc["controller_pid"], signal.SIGKILL)
        time.sleep(0.5)

        # Respawn the service process the way serve.core.up does.
        proc = subprocess.Popen(
            [sys.executable, "-m", "skypilot_tpu.serve.service",
             "--service-name", name,
             "--task-yaml", svc["task_yaml_path"],
             "--lb-port", str(svc["lb_port"])],
            env=dict(os.environ), start_new_session=True)

        # Wait until the restarted controller has actually taken over
        # (its pid recorded) — only then is a READY row ITS verdict, not
        # a stale pre-crash one.
        deadline = time.time() + 60
        while time.time() < deadline:
            row = serve_state.get_service(name)
            if row and row["controller_pid"] == proc.pid:
                break
            time.sleep(0.2)
        assert serve_state.get_service(name)["controller_pid"] == proc.pid

        # The restarted controller adopts both replicas: same ids, same
        # clusters, READY again, and answering through the (replaced) LB.
        deadline = time.time() + 90
        adopted = False
        while time.time() < deadline:
            reps = serve_state.get_replicas(name)
            now = {r["replica_id"]: r["cluster_name"] for r in reps
                   if r["status"] == ReplicaStatus.READY}
            if (now == before and serve_state.get_service(name)["status"]
                    == ServiceStatus.READY):
                adopted = True
                break
            time.sleep(0.3)
        assert adopted, f"replicas not adopted: {reps} vs {before}"
        clusters_after = sorted(
            r["name"] for r in global_user_state.get_clusters())
        assert clusters_after == clusters_before, "fleet was relaunched"
        status, _ = _get(endpoint + "/")
        assert status == 200
    finally:
        serve_core.down([name], timeout=60)
        if proc is not None:
            proc.wait(timeout=30)


@pytest.mark.usefixtures("tmp_state_dir")
def test_superseded_controller_stands_down():
    """Spawn a SECOND service process while the first is still alive
    (the crash-recovery respawn racing a not-actually-dead predecessor —
    judging round 4 found three 6-hour orphans from exactly this). The
    newest controller_pid stamp wins: the old controller must exit
    within ~two ticks WITHOUT tearing down the fleet it no longer owns
    (VERDICT r4 weak #1 / next #2)."""
    import os
    import signal
    import subprocess
    import sys

    name, endpoint = serve_core.up(_server_task(replicas=1), "svc-super",
                                   controller="local")
    proc = None
    try:
        serve_core.wait_ready(name, timeout=90)
        svc = serve_state.get_service(name)
        old_pid = svc["controller_pid"]
        reps_before = {r["replica_id"]: r["cluster_name"]
                       for r in serve_state.get_replicas(name)
                       if r["status"] == ReplicaStatus.READY}
        assert reps_before

        # Old controller NOT killed — spawn a competitor directly.
        proc = subprocess.Popen(
            [sys.executable, "-m", "skypilot_tpu.serve.service",
             "--service-name", name,
             "--task-yaml", svc["task_yaml_path"],
             "--lb-port", str(svc["lb_port"])],
            env=dict(os.environ), start_new_session=True)

        deadline = time.time() + 60
        while time.time() < deadline:
            row = serve_state.get_service(name)
            if row and row["controller_pid"] == proc.pid:
                break
            time.sleep(0.2)
        assert serve_state.get_service(name)["controller_pid"] == proc.pid

        # Old controller exits within ~two ticks of the new stamp
        # (tick=0.3s here; generous deadline for CI jitter). It becomes
        # a zombie of the pytest process (serve_core.up never waits), so
        # liveness is judged by cmdline — a zombie's is empty.
        from skypilot_tpu.utils import proc_utils
        deadline = time.time() + 30
        old_gone = False
        while time.time() < deadline:
            if not proc_utils.cmdline_matches(
                    old_pid, "skypilot_tpu.serve.service"):
                old_gone = True
                break
            time.sleep(0.1)
        assert old_gone, "superseded controller still alive"

        # It stood down WITHOUT touching the fleet: same replicas, same
        # clusters, service row intact, endpoint still answering through
        # the new owner.
        row = serve_state.get_service(name)
        assert row is not None, "old controller removed the service row"
        deadline = time.time() + 60
        ok = False
        while time.time() < deadline:
            now = {r["replica_id"]: r["cluster_name"]
                   for r in serve_state.get_replicas(name)
                   if r["status"] == ReplicaStatus.READY}
            if now == reps_before and row["controller_pid"] == proc.pid:
                ok = True
                break
            time.sleep(0.3)
            row = serve_state.get_service(name)
        assert ok, "fleet was disturbed by the superseded controller"
        # The LB port just changed hands (new service killed the old LB
        # and its respawn uses backoff): allow it a moment to rebind.
        deadline = time.time() + 30
        status = None
        while time.time() < deadline:
            try:
                status, _ = _get(endpoint + "/")
                if status == 200:
                    break
            except (urllib.error.URLError, ConnectionError, OSError):
                pass
            time.sleep(0.5)
        assert status == 200, "endpoint dead after controller handoff"
    finally:
        serve_core.down([name], timeout=60)
        if proc is not None:
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()


@pytest.mark.usefixtures("tmp_state_dir")
def test_sync_carries_upstream_timeout():
    """The per-service LB upstream timeout (service_spec
    upstream_timeout_seconds) rides the /sync reply (VERDICT r3 weak #4:
    the 120s constant 502'd slow-first-byte replicas)."""
    import json
    import urllib.request
    from skypilot_tpu.serve.controller import SkyServeController

    task = _server_task(replicas=1)
    spec = SkyServiceSpec(readiness_path="/", min_replicas=1,
                          upstream_timeout_seconds=600)
    controller = SkyServeController("svc-sync-t", spec, task)
    port = controller.start_sync_server()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/sync",
        data=json.dumps({"request_timestamps": []}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        payload = json.loads(resp.read())
    assert payload["upstream_timeout"] == 600
    # Malformed sync: 400, and it must NOT stamp the caught-up gate.
    before = controller._last_sync_at
    bad = urllib.request.Request(
        f"http://127.0.0.1:{port}/sync", data=b"not json{",
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        urllib.request.urlopen(bad, timeout=5)
        code = 200
    except urllib.error.HTTPError as e:
        code = e.code
    assert code == 400
    assert controller._last_sync_at == before


@pytest.mark.usefixtures("tmp_state_dir")
def test_fallback_requires_spot_task():
    """On-demand fallback knobs on a non-spot task are rejected at
    `serve up` (never silently converted to spot replicas)."""
    from skypilot_tpu import exceptions
    task = _server_task(replicas=1)
    task.service = SkyServiceSpec(readiness_path="/", min_replicas=1,
                                  dynamic_ondemand_fallback=True)
    with pytest.raises(exceptions.InvalidTaskError, match="use_spot"):
        serve_core.up(task, "svc-bad-fallback", controller="local")


@pytest.mark.usefixtures("tmp_state_dir")
def test_spot_fallback_rolling_update():
    """Rolling update of a dynamic-fallback spot service: pools stay
    version-aware (new-version capacity comes up as surge), the
    backfill never launches an on-demand fleet for old spot that is
    still READY (ready-spot counts across versions), service stays
    READY, and the fleet settles to the same 1 spot + 1 on-demand."""
    def spot_task(body):
        task = Task("spot-roll", run=(
            f'cd $(mktemp -d) && echo "{body}" > index.html && '
            'exec python3 -m http.server $SKYPILOT_SERVE_REPLICA_PORT'))
        task.set_resources(Resources(cloud="local", use_spot=True))
        task.service = SkyServiceSpec(readiness_path="/",
                                      initial_delay_seconds=60,
                                      min_replicas=2,
                                      base_ondemand_fallback_replicas=1,
                                      dynamic_ondemand_fallback=True)
        return task

    name, endpoint = serve_core.up(spot_task("v1"), "svc-sproll",
                                   controller="local")
    try:
        serve_core.wait_ready(name, timeout=90)
        deadline = time.time() + 60
        steady = False
        while time.time() < deadline:
            reps = serve_state.get_replicas(name)
            if (len(reps) == 2 and all(
                    r["status"] == ReplicaStatus.READY for r in reps)):
                steady = True
                break
            time.sleep(0.3)
        assert steady, f"v1 fleet never fully READY: {reps}"

        version = serve_core.update(spot_task("v2"), name,
                                    controller="local")
        assert version == 2

        max_od_alive = 0
        deadline = time.time() + 120
        rolled = False
        while time.time() < deadline:
            try:
                status, _ = _get(endpoint + "/")
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 200       # availability never dips
            reps = serve_state.get_replicas(name)
            od = [r for r in reps if not r["is_spot"]]
            max_od_alive = max(max_od_alive, len(od))
            ready_v2 = [r for r in reps
                        if r["status"] == ReplicaStatus.READY
                        and r["version"] == 2]
            if (len(reps) == 2 and len(ready_v2) == 2):
                rolled = True
                break
            time.sleep(0.3)
        assert rolled, f"rollout incomplete: {serve_state.get_replicas(name)}"
        reps = serve_state.get_replicas(name)
        spot = [r for r in reps if r["is_spot"]]
        od = [r for r in reps if not r["is_spot"]]
        assert len(spot) == 1 and len(od) == 1
        # Surge is bounded: old od + its v2 replacement — never a
        # dynamic-backfill fleet on top (old READY spot counts).
        assert max_od_alive <= 2, max_od_alive
        bodies = {_get(endpoint + "/")[1].strip() for _ in range(4)}
        assert bodies == {"v2"}, bodies
    finally:
        serve_core.down([name], timeout=60)


def test_lb_endpoint_resolves_via_query_ports(monkeypatch):
    """Cluster-mode endpoints ride the provision SPI's query_ports, so
    a kubernetes-hosted controller reports node_ip:nodePort instead of
    its in-cluster-only pod IP."""
    import skypilot_tpu.provision as provision_api
    from skypilot_tpu.provision.common import ClusterInfo, InstanceInfo

    info = ClusterInfo(provider_name="kubernetes", cluster_name="ctl",
                       region=None, zone=None,
                       instances={"p0": InstanceInfo(
                           instance_id="p0", internal_ip="10.4.0.5",
                           external_ip=None, slice_id=0, host_index=0)},
                       head_instance_id="p0", provider_config={})

    class _Handle:
        provider_name = "kubernetes"
        cluster_name = "ctl"
        cluster_info = info

    monkeypatch.setattr(
        provision_api, "query_ports",
        lambda prov, name, ports, head, cfg: {30005: "34.1.2.3:30005"})
    assert serve_core._lb_endpoint(_Handle(), 30005) == \
        "http://34.1.2.3:30005"
    # query_ports empty (ingress gone): head-ip fallback, not a crash.
    monkeypatch.setattr(provision_api, "query_ports",
                        lambda *a, **k: {})
    assert serve_core._lb_endpoint(_Handle(), 30005) == \
        "http://10.4.0.5:30005"


def test_serve_controller_resources_carry_lb_range(tmp_state_dir,
                                                   monkeypatch):
    """The serve controller cluster's resources include the LB port
    range, so provisioning it opens ingress for every future service's
    endpoint without user action (VERDICT r4 #1 done-bar)."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu.utils import controller_utils

    res = controller_utils.controller_resources(
        controller_utils.Controllers.SERVE)
    assert serve_core.LB_PORT_RANGE_SPEC in res.ports
    # Config-specified controller resources get the range appended too.
    monkeypatch.setattr(
        config_lib, "get_nested",
        lambda keys, default=None:
        {"cloud": "gcp", "accelerators": "tpu-v5e-8"}
        if keys == ("serve", "controller", "resources") else default)
    res = controller_utils.controller_resources(
        controller_utils.Controllers.SERVE)
    assert res.cloud == "gcp"
    assert serve_core.LB_PORT_RANGE_SPEC in res.ports
    # The jobs controller does NOT host LBs: no range.
    assert serve_core.LB_PORT_RANGE_SPEC not in \
        controller_utils.controller_resources(
            controller_utils.Controllers.JOBS).ports


def test_serve_controller_lb_range_gated_on_port_support(tmp_state_dir,
                                                         monkeypatch):
    """Clouds without OPEN_PORTS (docker publishes ports out of band)
    must NOT get the LB range injected — the optimizer would reject the
    controller resources outright, bricking `serve up` on a docker
    controller (mirrors replica_managers._cloud_manages_ports)."""
    from skypilot_tpu import config as config_lib
    from skypilot_tpu.utils import controller_utils

    monkeypatch.setattr(
        config_lib, "get_nested",
        lambda keys, default=None:
        {"cloud": "docker"}
        if keys == ("serve", "controller", "resources") else default)
    res = controller_utils.controller_resources(
        controller_utils.Controllers.SERVE)
    assert res.cloud == "docker"
    assert serve_core.LB_PORT_RANGE_SPEC not in res.ports
    # Explicit user-specified ports pass through untouched.
    assert res.ports == ()


def test_replica_launch_injects_serving_port(tmp_state_dir, monkeypatch):
    """Replica clusters' resources carry the serving port, so the
    provision path opens it for LB probes/proxying from the controller
    host (VERDICT r4 #1: the LB reaches <replica_ip>:<port> from
    OUTSIDE the replica's network on real clouds)."""
    from skypilot_tpu.serve import replica_managers

    task = _server_task(replicas=1)
    task.set_resources(Resources(cloud="gcp",
                                 accelerator="tpu-v5e-8",
                                 zone="us-east5-b",
                                 ports=("9999",)))
    mgr = replica_managers.SkyPilotReplicaManager(
        "svc-inj", task.service, task)
    captured = {}

    def fake_launch(t, cluster_name=None, detach_run=None,
                    stream_logs=None):
        captured["ports"] = next(iter(t.resources)).ports
        raise RuntimeError("stop before provisioning")

    monkeypatch.setattr(replica_managers.execution, "launch",
                        fake_launch)
    mgr.scale_up(1)
    for t in list(mgr._threads):
        t.join(timeout=30)
    # Task port 9999 is the replica port (first in ports) and stays the
    # only entry — no duplicate injection.
    assert captured["ports"] == ("9999",)

    # Without explicit ports, the default port 8080 is injected.
    task2 = _server_task(replicas=1)
    task2.set_resources(Resources(cloud="gcp",
                                  accelerator="tpu-v5e-8",
                                  zone="us-east5-b"))
    mgr2 = replica_managers.SkyPilotReplicaManager(
        "svc-inj2", task2.service, task2)
    mgr2.scale_up(1)
    for t in list(mgr2._threads):
        t.join(timeout=30)
    assert captured["ports"] == ("8080",)


@pytest.mark.usefixtures("tmp_state_dir")
def test_serve_logs_targets():
    """`serve logs` reaches all three processes: controller log,
    load-balancer log (--load-balancer), and a replica's job logs
    (reference: sky serve logs --controller/--load-balancer)."""
    name, endpoint = serve_core.up(_server_task(replicas=1), "svc-logs",
                                   controller="local")
    try:
        serve_core.wait_ready(name, timeout=90)
        from skypilot_tpu.utils import paths
        # Controller + LB logs exist as separate files.
        assert (paths.logs_dir() / "serve" / f"{name}.log").exists()
        assert (paths.logs_dir() / "serve" / f"{name}-lb.log").exists()
        # The local tailer resolves each target (no-follow: one pass).
        assert serve_core._logs_local(name, None, follow=False,
                                      target="controller") == 0
        assert serve_core._logs_local(name, None, follow=False,
                                      target="load_balancer") == 0
    finally:
        serve_core.down([name], timeout=60)


# ---------------------------------------------------------------------------
@pytest.mark.usefixtures("tmp_state_dir")
def test_preempt_notice_replace_ahead_e2e():
    """ISSUE 19 (preemption-notice proactive drain) at the controller:
    a READY replica starts advertising ``preempt_notice: true`` on its
    health endpoint (what serve_llm's metadata watcher surfaces when
    the provider announces the kill) — the next probe flips it
    DRAINING, the SAME reconcile loop launches the replacement ahead
    of the kill, and the service returns to full strength with the
    noticed replica gone."""
    import json as json_lib
    import os

    from skypilot_tpu.observability import events

    # Replica server: /health-style JSON on every GET, advertising the
    # preemption notice iff the per-port flag file exists — the test's
    # stand-in for the provider metadata signal.
    task = Task("notice-svc", run=(
        "cd $(mktemp -d) && cat > srv.py <<'EOF'\n"
        "import http.server, json, os\n"
        "port = int(os.environ['SKYPILOT_SERVE_REPLICA_PORT'])\n"
        "flag = '/tmp/stpu-preempt-%d' % port\n"
        "class H(http.server.BaseHTTPRequestHandler):\n"
        "    def log_message(self, *a): pass\n"
        "    def do_GET(self):\n"
        "        doc = {'status': 'ok', 'port': port}\n"
        "        if os.path.exists(flag):\n"
        "            doc['preempt_notice'] = True\n"
        "        body = json.dumps(doc).encode()\n"
        "        self.send_response(200)\n"
        "        self.send_header('Content-Type', 'application/json')\n"
        "        self.send_header('Content-Length', str(len(body)))\n"
        "        self.end_headers()\n"
        "        self.wfile.write(body)\n"
        "http.server.HTTPServer(('', port), H).serve_forever()\n"
        "EOF\n"
        "exec python3 srv.py"))
    task.set_resources(Resources(cloud="local"))
    task.service = SkyServiceSpec(readiness_path="/",
                                  initial_delay_seconds=60,
                                  min_replicas=2)
    name, endpoint = serve_core.up(task, "svc-notice",
                                   controller="local")
    flag = None
    try:
        serve_core.wait_ready(name, timeout=90)
        deadline = time.time() + 60
        while time.time() < deadline:
            reps = serve_state.get_replicas(name)
            ready = [r for r in reps
                     if r["status"] == ReplicaStatus.READY]
            if len(ready) == 2:
                break
            time.sleep(0.3)
        else:
            pytest.fail(f"never reached 2 READY: {reps}")

        victim = ready[0]
        vid = victim["replica_id"]
        vport = int(victim["url"].rsplit(":", 1)[1])
        # Confirm the victim is serving and notice-free, then land the
        # provider's preemption notice.
        _, body = _get(victim["url"] + "/")
        assert "preempt_notice" not in json_lib.loads(body)
        flag = f"/tmp/stpu-preempt-{vport}"
        with open(flag, "w"):
            pass

        # Replace-ahead: the victim leaves the ready set and a NEW
        # replica id reaches READY — service back to 2 READY without
        # ever waiting for the kill itself.
        deadline = time.time() + 90
        replaced = False
        while time.time() < deadline:
            reps = serve_state.get_replicas(name)
            ready_ids = {r["replica_id"] for r in reps
                         if r["status"] == ReplicaStatus.READY}
            if vid not in ready_ids and len(ready_ids) == 2:
                replaced = True
                break
            time.sleep(0.3)
        assert replaced, f"no replace-ahead: {reps}"
        evs = [e["event"] for e in events.read(
            kind="replica", name=f"{name}/{vid}", limit=None)]
        assert "preempt_notice" in evs
        assert serve_state.get_service(name)["status"] == \
            ServiceStatus.READY
    finally:
        if flag:
            try:
                os.remove(flag)
            except FileNotFoundError:
                pass
        serve_core.down([name], timeout=60)
