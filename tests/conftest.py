"""Test harness: force an 8-device CPU platform so every sharding/mesh test
runs hermetically without TPU hardware.

Mirrors the reference's hermetic strategy (tests/common.py in the reference
monkeypatches all clouds enabled + pinned catalogs); here the analog is a
virtual 8-device CPU mesh for gang/sharding tests plus tmpdir-backed state
DBs for orchestration tests.
"""
import os

# jax may already be imported by the interpreter's sitecustomize (TPU
# tunnel); the config update below still forces the CPU platform as long as
# no backend has been instantiated yet. XLA_FLAGS is read at CPU-client
# creation, which is also still ahead of us.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache — the same one bench.py and the bench
# tools use, so tier-1 reruns (and recipe subprocesses, which inherit the
# env) skip recompiling the suite's hundreds of tiny programs. Program
# cache keys include backend + jax version, so CPU test programs never
# collide with tunneled-TPU bench entries. Opt out / redirect by setting
# JAX_COMPILATION_CACHE_DIR yourself (empty string disables).
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.expanduser("~/.cache/stpu_jax_cache"))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          0.5)
        # Subprocess tests (recipes, gang followers) pick it up too.
        os.environ["JAX_COMPILATION_CACHE_DIR"] = os.path.expanduser(
            "~/.cache/stpu_jax_cache")
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
        os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    except Exception:  # noqa: BLE001 — cache is an optimization
        pass

# Don't spawn the on-host daemon for every local cluster the suite
# launches; daemon/autostop tests opt back in via monkeypatch.
os.environ.setdefault("STPU_DISABLE_DAEMON", "1")

import pytest  # noqa: E402

# Session-detached processes the suite spawns (serve controllers via
# start_new_session=True, LBs, gang drivers). A killed pytest run (ctrl-C,
# OOM, timeout) skips their `finally` teardown and leaves them probing
# forever — judging round 4 found three 6-hour-old controllers from
# exactly this. Scope: only processes whose STPU_HOME points into a
# pytest tmpdir, so a real serve deployment on the same host is never
# touched. (Corollary: suite slices must run SEQUENTIALLY — a parallel
# pytest invocation's processes would match this scope.)
_REAP_CMD_MARKERS = ("skypilot_tpu.serve.service",
                     "skypilot_tpu.serve.load_balancer",
                     "skypilot_tpu.agent.gang_exec",
                     "skypilot_tpu.agent.daemon",
                     "skypilot_tpu.agent.exec_server")


def _reap_stray_test_processes() -> list:
    import signal
    reaped = []
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        pid = int(entry)
        if pid == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ").decode(
                    "utf-8", "replace")
            if not any(m in cmd for m in _REAP_CMD_MARKERS):
                continue
            with open(f"/proc/{pid}/environ", "rb") as f:
                env_entries = f.read().decode("utf-8",
                                              "replace").split("\x00")
        except OSError:  # exited mid-scan, or not ours to read
            continue
        stpu_home = next((e[len("STPU_HOME="):] for e in env_entries
                          if e.startswith("STPU_HOME=")), "")
        # The VALUE must point into a pytest tmpdir — 'pytest-'
        # elsewhere in the environment (a venv path, say) must not make
        # a real deployment reapable.
        if "pytest-" not in stpu_home:
            continue
        try:
            # start_new_session=True makes these group leaders; kill the
            # whole group so their own children die too.
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                continue
        reaped.append((pid, cmd.strip()))
    return reaped


def pytest_sessionstart(session):
    del session
    for pid, cmd in _reap_stray_test_processes():
        print(f"[conftest] reaped stray test process from a previous "
              f"run: pid {pid} ({cmd})")


def pytest_sessionfinish(session, exitstatus):
    del session, exitstatus
    for pid, cmd in _reap_stray_test_processes():
        print(f"[conftest] reaped leftover test process: pid {pid} "
              f"({cmd})")


def pytest_addoption(parser):
    """Opt-in real-cloud smoke tests (reference: tests/conftest.py:49-80
    --aws/--gcp/--tpu flags gating tests/test_smoke.py)."""
    parser.addoption(
        "--gcp-live", action="store_true", default=False,
        help="run tests that provision REAL GCP TPUs (costs money; "
             "needs gcloud credentials + a project with TPU quota)")
    parser.addoption(
        "--kind-live", action="store_true", default=False,
        help="run the Kind-backed kubernetes smoke (needs kind + "
             "kubectl + docker on PATH; free, local)")


def pytest_collection_modifyitems(config, items):
    gates = (("gcp_live", "--gcp-live"), ("kind_live", "--kind-live"))
    for marker, flag in gates:
        if config.getoption(flag):
            continue
        skip = pytest.mark.skip(
            reason=f"live smoke test: pass {flag} to run")
        for item in items:
            if marker in item.keywords:
                item.add_marker(skip)


@pytest.fixture
def tmp_state_dir(tmp_path, monkeypatch):
    """Redirect all client-side state (~/.stpu) into a tmpdir."""
    monkeypatch.setenv("STPU_HOME", str(tmp_path / ".stpu"))
    from skypilot_tpu.utils import paths
    paths.reset_for_tests()
    yield tmp_path / ".stpu"
    paths.reset_for_tests()
