"""Test harness: force an 8-device CPU platform so every sharding/mesh test
runs hermetically without TPU hardware.

Mirrors the reference's hermetic strategy (tests/common.py in the reference
monkeypatches all clouds enabled + pinned catalogs); here the analog is a
virtual 8-device CPU mesh for gang/sharding tests plus tmpdir-backed state
DBs for orchestration tests.
"""
import os

# jax may already be imported by the interpreter's sitecustomize (TPU
# tunnel); the config update below still forces the CPU platform as long as
# no backend has been instantiated yet. XLA_FLAGS is read at CPU-client
# creation, which is also still ahead of us.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Don't spawn the on-host daemon for every local cluster the suite
# launches; daemon/autostop tests opt back in via monkeypatch.
os.environ.setdefault("STPU_DISABLE_DAEMON", "1")

import pytest  # noqa: E402


def pytest_addoption(parser):
    """Opt-in real-cloud smoke tests (reference: tests/conftest.py:49-80
    --aws/--gcp/--tpu flags gating tests/test_smoke.py)."""
    parser.addoption(
        "--gcp-live", action="store_true", default=False,
        help="run tests that provision REAL GCP TPUs (costs money; "
             "needs gcloud credentials + a project with TPU quota)")
    parser.addoption(
        "--kind-live", action="store_true", default=False,
        help="run the Kind-backed kubernetes smoke (needs kind + "
             "kubectl + docker on PATH; free, local)")


def pytest_collection_modifyitems(config, items):
    gates = (("gcp_live", "--gcp-live"), ("kind_live", "--kind-live"))
    for marker, flag in gates:
        if config.getoption(flag):
            continue
        skip = pytest.mark.skip(
            reason=f"live smoke test: pass {flag} to run")
        for item in items:
            if marker in item.keywords:
                item.add_marker(skip)


@pytest.fixture
def tmp_state_dir(tmp_path, monkeypatch):
    """Redirect all client-side state (~/.stpu) into a tmpdir."""
    monkeypatch.setenv("STPU_HOME", str(tmp_path / ".stpu"))
    from skypilot_tpu.utils import paths
    paths.reset_for_tests()
    yield tmp_path / ".stpu"
    paths.reset_for_tests()
