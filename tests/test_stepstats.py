"""Per-step performance telemetry + flight recorder (ISSUE 11).

Acceptance pinned here:
  * an injected ``engine.step`` crash (existing fault seam) produces a
    flight-recorder dump with the terminal exception and >= 1
    pre-crash step record, and ``stpu perf show`` renders it;
  * ``GET /perf`` serves the phase breakdown from the replica and the
    LB merges every ready replica's /perf into one document;
  * disarmed, the engine hot path is provably stepstats-free
    (monkeypatch-bomb, the tracing/fault-injection pattern) and the
    armed engine's tok/s stays within noise of unarmed (slow-marked).
"""
import json
import threading
import time
import urllib.request

import pytest
from click.testing import CliRunner

from skypilot_tpu.observability import stepstats
from skypilot_tpu.utils import fault_injection


@pytest.fixture
def armed(tmp_state_dir):
    stepstats.arm(ring=256, sync_every=0)
    stepstats.reset()
    yield tmp_state_dir
    stepstats.disarm()
    stepstats.reset()


def _tiny_llm():
    import jax

    from skypilot_tpu.models import llama
    cfg = llama.LlamaConfig.tiny(vocab_size=128)
    params = llama.init(cfg, jax.random.key(0))
    return cfg, params


# ------------------------------------------------------------ ring unit
def test_ring_record_and_snapshot(armed):
    for i in range(300):            # ring=256: oldest 44 evicted
        stepstats.record(dur=0.001, phase="decode", live_slots=2,
                         queue_depth=1, decode_tokens=2)
    snap = stepstats.snapshot()
    assert snap["armed"] is True
    assert snap["steps"] == 256
    assert snap["total_steps"] == 300
    assert snap["phases"]["decode"]["steps"] == 256
    assert snap["phases"]["decode"]["seconds"] == pytest.approx(
        0.256, rel=1e-6)
    assert 0.0 < snap["busy_fraction"] <= 1.0
    assert snap["occupancy"]["mean"] == 2.0
    assert snap["queue_depth"] == 1
    # Eviction kept the running sums consistent with the resident set.
    assert sum(p["steps"] for p in snap["phases"].values()) == 256


def test_ring_mixed_phases_and_tokens(armed):
    stepstats.record(dur=0.002, phase="prefill", live_slots=1,
                     queue_depth=0, prefill_tokens=64)
    stepstats.record(dur=0.001, phase="decode", live_slots=3,
                     queue_depth=0, decode_tokens=3)
    stepstats.record(dur=0.003, phase="mixed", live_slots=3,
                     queue_depth=0, prefill_tokens=64,
                     decode_tokens=3)
    snap = stepstats.snapshot()
    assert set(snap["phases"]) == {"prefill", "decode", "mixed"}
    shares = sum(p["share"] for p in snap["phases"].values())
    assert shares == pytest.approx(1.0, abs=0.01)
    assert snap["tokens_per_sec"]["prefill"] > 0
    assert snap["tokens_per_sec"]["decode"] > 0


def test_sync_due_cadence(armed):
    stepstats.arm(ring=256, sync_every=3)
    assert [stepstats.sync_due() for _ in range(7)] == [
        False, False, True, False, False, True, False]
    stepstats.arm(ring=256, sync_every=0)
    assert not any(stepstats.sync_due() for _ in range(10))


def test_sampled_sync_times_the_wait(armed):
    class _Arr:
        def __init__(self):
            self.calls = 0

        def block_until_ready(self):
            self.calls += 1
            time.sleep(0.01)

    arr = _Arr()
    waited = stepstats.sampled_sync(arr)
    assert arr.calls == 1
    assert waited >= 0.009
    # Non-array values (no block_until_ready) never raise.
    assert stepstats.sampled_sync(object()) >= 0.0


def test_derived_metrics_exposed(armed):
    from skypilot_tpu.observability import metrics, promtext
    stepstats.record(dur=0.002, phase="decode", live_slots=4,
                     queue_depth=0, decode_tokens=4,
                     dispatch_s=0.0002, device_s=0.0015)
    families = promtext.parse(metrics.render())
    assert promtext.histogram(
        families, "stpu_engine_step_seconds",
        phase="decode").count > 0
    assert promtext.value(
        families, "stpu_engine_busy_fraction") > 0
    assert "stpu_engine_phase_tokens_per_sec" in families
    assert promtext.histogram(
        families, "stpu_engine_step_dispatch_seconds").count > 0
    assert promtext.histogram(
        families, "stpu_engine_step_device_seconds").count > 0


# --------------------------------------------------------- engine wired
@pytest.mark.usefixtures("tmp_state_dir")
def test_disarmed_engine_is_stepstats_free(monkeypatch):
    """Mirror of the tracing/fault-injection zero-cost guarantee: with
    stepstats unarmed, a full engine request (admission, chunked
    prefill, decode steps, slot free) never reaches the module past
    the ENABLED flag — any record/record_admission/sync call trips the
    monkeypatched bomb."""
    from skypilot_tpu.serve.decode_engine import DecodeEngine

    assert not stepstats.ENABLED

    def bomb(*args, **kwargs):
        raise AssertionError(
            "stepstats reached while unarmed (hot path must guard on "
            "stepstats.ENABLED)")

    monkeypatch.setattr(stepstats, "record", bomb)
    monkeypatch.setattr(stepstats, "record_admission", bomb)
    monkeypatch.setattr(stepstats, "sampled_sync", bomb)
    monkeypatch.setattr(stepstats, "sync_due", bomb)

    cfg, params = _tiny_llm()
    engine = DecodeEngine(cfg, params, slots=2, max_seq=64,
                          prefill_chunk=8).start()
    try:
        toks = engine.submit([1, 2, 3], max_tokens=4).result(
            timeout=600)
        assert len(toks) == 4
    finally:
        engine.shutdown()


def test_jitted_steps_are_stepstats_free():
    """The jitted programs themselves carry no telemetry code —
    recording rides the host-side supervisor loop only."""
    import inspect

    from skypilot_tpu.serve import decode_engine
    for fn in (decode_engine._engine_step, decode_engine._paged_step,
               decode_engine._prefill_chunk):
        assert "stepstats" not in inspect.getsource(fn)


def test_armed_engine_records_steps_and_admissions(armed):
    from skypilot_tpu.serve.decode_engine import DecodeEngine

    stepstats.arm(ring=512, sync_every=4)
    cfg, params = _tiny_llm()
    engine = DecodeEngine(cfg, params, slots=2, max_seq=96,
                          prefill_chunk=16).start()
    try:
        reqs = [engine.submit([1 + i, 2, 3], max_tokens=8)
                for i in range(3)]
        total = sum(len(r.result(timeout=600)) for r in reqs)
        assert total == 24
    finally:
        engine.shutdown()
    snap = stepstats.snapshot()
    assert snap["steps"] > 0
    # Both phases showed up: chunked prefill AND batched decode.
    assert "decode" in snap["phases"] or "mixed" in snap["phases"]
    assert snap["tokens_per_sec"]["decode"] > 0
    # sync_every=4 with >= 8 decode steps: at least one sampled split.
    assert snap.get("sync", {}).get("samples", 0) >= 1
    assert snap.get("dispatch_ms_mean") is not None
    admits = stepstats.admissions_tail()
    assert len(admits) >= 3        # warmup + the three requests
    assert admits[-1]["prompt_tokens"] == 3
    assert admits[-1]["max_tokens"] == 8
    assert admits[-1]["queue_wait_s"] >= 0.0


def test_engine_crash_writes_flight_dump_and_cli_renders_it(armed):
    """THE acceptance path: injected engine.step crash -> dump with
    terminal exception + pre-crash step records -> `stpu perf show`
    renders it; the engine_failed event references the dump."""
    from skypilot_tpu import cli
    from skypilot_tpu.observability import events
    from skypilot_tpu.serve import decode_engine
    from skypilot_tpu.serve.decode_engine import (DecodeEngine,
                                                  EngineError,
                                                  EngineSupervisor)

    cfg, params = _tiny_llm()
    sup = EngineSupervisor(
        lambda: DecodeEngine(cfg, params, slots=2, max_seq=96,
                             prefill_chunk=16),
        max_restarts=1, backoff_base=0.05,
        poll_interval=0.02).start()
    try:
        # Healthy request first: the ring must hold PRE-crash steps.
        sup.engine.submit([1, 2, 3], max_tokens=6).result(timeout=600)
        with fault_injection.inject("engine.step", times=1):
            req = sup.submit([4, 5, 6], max_tokens=6)
            with pytest.raises(EngineError):
                req.result(timeout=600)
        # Wait for the supervisor's engine_failed event (it carries
        # the flight-dump reference) — the dump itself is written
        # synchronously on the crash path before the request fails.
        deadline = time.time() + 10
        while time.time() < deadline:
            if any(r.get("event") == "engine_failed"
                   for r in events.read(kind="engine", limit=None)):
                break
            time.sleep(0.05)
    finally:
        sup.shutdown()
        fault_injection.clear()
    dumps = stepstats.list_dumps()
    assert dumps, "engine crash produced no flight-recorder dump"
    doc = stepstats.read_dump()
    assert doc["reason"] == "engine_crash"
    assert "InjectedFault" in doc["error"]
    assert len(doc["steps"]) >= 1
    assert doc["snapshot"]["steps"] >= 1
    # The lifecycle event references the dump path.
    failed = [r for r in events.read(kind="engine", limit=None)
              if r.get("event") == "engine_failed"]
    assert failed and failed[-1].get("flightrec")
    assert failed[-1]["flightrec"].endswith(".json")

    runner = CliRunner()
    out = runner.invoke(cli.cli, ["perf", "show"])
    assert out.exit_code == 0, out.output
    assert "engine_crash" in out.output
    assert "InjectedFault" in out.output
    assert "decode" in out.output or "prefill" in out.output

    out = runner.invoke(cli.cli, ["perf", "dump"])
    assert out.exit_code == 0, out.output
    assert dumps[-1] in out.output
    out = runner.invoke(cli.cli, ["perf", "dump", dumps[-1]])
    assert out.exit_code == 0
    assert json.loads(out.output)["reason"] == "engine_crash"
    # del the decode_engine ref keeps linters honest about the import
    del decode_engine


def test_dump_flight_roundtrip_and_prefix_resolution(armed):
    stepstats.record(dur=0.001, phase="decode", live_slots=1,
                     queue_depth=0, decode_tokens=1)
    path = stepstats.dump_flight("sigterm", error=None)
    assert path is not None and path.endswith(".json")
    doc = stepstats.read_dump()
    assert doc["reason"] == "sigterm"
    assert doc["steps"][-1]["decode_tokens"] == 1
    # Unique-prefix resolution + clean errors.
    name = stepstats.list_dumps()[-1]
    assert stepstats.read_dump(name[:20])["reason"] == "sigterm"
    with pytest.raises(FileNotFoundError):
        stepstats.read_dump("zzz-no-such-dump")


@pytest.mark.usefixtures("tmp_state_dir")
def test_read_dump_without_dumps_raises(monkeypatch):
    with pytest.raises(FileNotFoundError):
        stepstats.read_dump()


def test_dump_retention_cap(armed, monkeypatch):
    """Crash/restart paths dump unconditionally, so retention must be
    bounded: only the newest KEEP_DUMPS survive replica churn."""
    monkeypatch.setattr(stepstats, "KEEP_DUMPS", 5)
    for i in range(9):
        assert stepstats.dump_flight("engine_crash",
                                     error=f"crash {i}")
    dumps = stepstats.list_dumps()
    assert len(dumps) == 5
    # The newest dump is the one kept last.
    assert stepstats.read_dump()["error"] == "crash 8"


def test_begin_profile_atomic_claim(armed):
    """POST /profile's claim must be atomic: the second claimant is
    refused (409 on the handler side) instead of both being promised a
    capture."""
    assert stepstats.begin_profile() is True
    assert stepstats.begin_profile() is False
    with pytest.raises(RuntimeError):
        stepstats.capture_profile(0.05)
    # The claimed path releases the slot on completion.
    class _P:
        @staticmethod
        def start_trace(path):
            pass

        @staticmethod
        def stop_trace():
            pass

    import jax
    orig = jax.profiler
    jax.profiler = _P
    try:
        stepstats.capture_profile(0.05, claimed=True)
        # Slot released on completion: claimable again.
        assert stepstats.begin_profile() is True
        stepstats.capture_profile(0.05, claimed=True)
    finally:
        jax.profiler = orig
    assert not stepstats._profile_active


# ------------------------------------------------- /perf + LB merge e2e
def test_replica_perf_endpoint_and_lb_merge(armed):
    import socket

    from skypilot_tpu.recipes import serve_llm
    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import (
        RoundRobinPolicy)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    stepstats.arm(ring=512, sync_every=4)
    cfg, params = _tiny_llm()
    port = free_port()
    httpd = serve_llm.serve(cfg, params, port, engine_slots=2,
                            prefix_cache_mb=0.0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    replica = f"http://127.0.0.1:{port}"
    lb = None
    try:
        deadline = time.time() + 600
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(replica + "/health",
                                            timeout=2) as resp:
                    if resp.status == 200:
                        break
            except Exception:
                pass
            time.sleep(0.1)
        body = json.dumps({"prompt": [1, 2, 3],
                           "max_tokens": 6}).encode()
        req = urllib.request.Request(
            replica + "/generate", data=body,
            headers={"Content-Type": "application/json"},
            method="POST")
        with urllib.request.urlopen(req, timeout=600) as resp:
            assert resp.status == 200

        with urllib.request.urlopen(replica + "/perf",
                                    timeout=10) as resp:
            doc = json.loads(resp.read())
        assert doc["armed"] is True
        assert doc["steps"] > 0
        assert doc["phases"]
        assert doc["engine"]["healthy"] is True

        # LB merge: one fetch of the service endpoint covers the stack.
        policy = RoundRobinPolicy()
        policy.set_ready_replicas([replica])
        lb = lb_lib.run_load_balancer(free_port(), policy,
                                      lb_lib.RequestRecorder())
        lb_url = f"http://127.0.0.1:{lb.server_address[1]}"
        with urllib.request.urlopen(lb_url + "/perf",
                                    timeout=10) as resp:
            merged = json.loads(resp.read())
        assert replica in merged["replicas"]
        assert merged["replicas"][replica]["phases"]
        assert merged["aggregate"]["replicas"] == 1
        assert merged["aggregate"]["phases"]
        assert merged["aggregate"]["tokens_per_sec"]["decode"] > 0
    finally:
        if lb is not None:
            lb.shutdown()
        if httpd.engine is not None:
            httpd.engine.shutdown()
        httpd.shutdown()


def test_lb_perf_merge_reports_dead_replica():
    """A replica that cannot be scraped is REPORTED in the /perf merge
    — an {"error": ...} entry under its url — and EXCLUDED from the
    aggregate, so a half-dead fleet reads as degraded instead of
    healthy-but-slower."""
    import http.server
    import socket

    from skypilot_tpu.serve import load_balancer as lb_lib
    from skypilot_tpu.serve.load_balancing_policies import (
        RoundRobinPolicy)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    perf_doc = {"armed": True, "steps": 4,
                "phases": {"decode": {"steps": 4, "seconds": 0.01}},
                "tokens_per_sec": {"prefill": 0.0, "decode": 100.0},
                "busy_fraction": 0.5}

    class _Replica(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps(perf_doc).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            del args

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Replica)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    live = f"http://127.0.0.1:{httpd.server_address[1]}"
    dead = f"http://127.0.0.1:{free_port()}"   # nothing listening

    policy = RoundRobinPolicy()
    policy.set_ready_replicas([live, dead])
    lb = lb_lib.run_load_balancer(free_port(), policy,
                                  lb_lib.RequestRecorder())
    try:
        lb_url = f"http://127.0.0.1:{lb.server_address[1]}"
        with urllib.request.urlopen(lb_url + "/perf",
                                    timeout=30) as resp:
            merged = json.loads(resp.read())
        assert merged["replicas"][live]["phases"]
        assert "error" in merged["replicas"][dead]
        assert merged["aggregate"]["replicas"] == 1   # healthy only
        assert merged["aggregate"]["errors"] == 1
        assert merged["aggregate"]["tokens_per_sec"]["decode"] == 100.0
    finally:
        lb.shutdown()
        httpd.shutdown()


def test_profile_endpoint_capture(armed, monkeypatch):
    import socket

    from skypilot_tpu.recipes import serve_llm

    calls = {"start": None, "stop": 0}

    class _FakeProfiler:
        @staticmethod
        def start_trace(path):
            calls["start"] = path

        @staticmethod
        def stop_trace():
            calls["stop"] += 1

    import jax
    monkeypatch.setattr(jax, "profiler", _FakeProfiler)

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    cfg, params = _tiny_llm()
    port = free_port()
    # engine_slots=0: the legacy path serves /profile too, and the
    # test stays light (no engine warmup compile).
    httpd = serve_llm.serve(cfg, params, port, engine_slots=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{port}/profile?seconds=0.05"
        req = urllib.request.Request(url, data=b"", method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 202
            doc = json.loads(resp.read())
        assert doc["profile_dir"]
        deadline = time.time() + 10
        while time.time() < deadline and calls["stop"] == 0:
            time.sleep(0.02)
        assert calls["start"] == doc["profile_dir"]
        assert calls["stop"] == 1
        # Malformed seconds -> clean 400, not a crash.
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/profile?seconds=abc",
            data=b"", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
        ei.value.read()
    finally:
        httpd.shutdown()


# -------------------------------------------------------------- CLI bits
def test_perf_cli_requires_target(tmp_state_dir):
    from skypilot_tpu import cli
    out = CliRunner().invoke(cli.cli, ["perf"])
    assert out.exit_code != 0
    assert "--url" in out.output


def test_perf_cli_renders_url_snapshot(armed):
    import http.server
    import socketserver

    doc = {"armed": True, "ring_size": 64, "steps": 10,
           "total_steps": 10, "window_s": 1.0, "busy_fraction": 0.5,
           "phases": {"decode": {"steps": 10, "seconds": 0.5,
                                 "share": 1.0}},
           "tokens_per_sec": {"prefill": 0.0, "decode": 40.0},
           "occupancy": {"mean": 2.0, "last": 2}, "queue_depth": 0,
           "admissions": 3}

    class _Perf(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = json.dumps(doc).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class _Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    srv = _Srv(("127.0.0.1", 0), _Perf)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        from skypilot_tpu import cli
        out = CliRunner().invoke(
            cli.cli,
            ["perf", "--url",
             f"http://127.0.0.1:{srv.server_address[1]}"])
        assert out.exit_code == 0, out.output
        assert "decode" in out.output
        assert "busy 50.0%" in out.output
    finally:
        srv.shutdown()


def test_metrics_watch_rate_annotation():
    from skypilot_tpu.cli import (_annotate_counter_rates,
                                  _counter_samples)
    t0 = ("# HELP stpu_x_total x\n# TYPE stpu_x_total counter\n"
          "stpu_x_total 10\n"
          'stpu_y_total{code="200"} 4\n'
          "# HELP stpu_g g\n# TYPE stpu_g gauge\nstpu_g 7\n")
    # stpu_y_total belongs to stpu_x_total's TYPE block only if it
    # shares the prefix — it does not, so only stpu_x_total counts.
    prev = _counter_samples(t0)
    assert prev == {"stpu_x_total": 10.0}
    t1 = t0.replace("stpu_x_total 10", "stpu_x_total 30")
    out = _annotate_counter_rates(t1, prev, dt=2.0)
    assert "stpu_x_total 30  (+10/s)" in out
    assert "stpu_g 7\n" in out          # gauges untouched
    # Counter reset renders (reset), not a negative rate.
    t2 = t0.replace("stpu_x_total 10", "stpu_x_total 3")
    out = _annotate_counter_rates(t2, prev, dt=2.0)
    assert "stpu_x_total 3  (reset)" in out


def test_env_knobs_registered():
    from skypilot_tpu.utils import env_contract
    for knob in ("STPU_STEPSTATS", "STPU_STEPSTATS_RING",
                 "STPU_STEPSTATS_SYNC_EVERY"):
        assert knob in env_contract.REGISTRY
    assert env_contract.REGISTRY["STPU_STEPSTATS_RING"].default == \
        "1024"


# ------------------------------------------------- loadgen mono stamps
def test_metrics_scraper_monotonic_stamps(tmp_state_dir):
    import http.server
    import socketserver

    from skypilot_tpu.benchmark.loadgen import MetricsScraper
    from skypilot_tpu.observability import metrics

    class _Metrics(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = metrics.render().encode()
            self.send_response(200)
            self.send_header("Content-Type", metrics.CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class _Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    srv = _Srv(("127.0.0.1", 0), _Metrics)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    tmp_state_dir.mkdir(parents=True, exist_ok=True)
    series = tmp_state_dir / "metrics.jsonl"
    try:
        scraper = MetricsScraper(
            f"http://127.0.0.1:{srv.server_address[1]}",
            interval=60.0, series_path=series)
        scraper._t0 = time.perf_counter()
        assert scraper.scrape_once() is not None
        time.sleep(0.05)
        assert scraper.scrape_once() is not None
    finally:
        srv.shutdown()
    # Monotonic window: positive, and independent of wall clock.
    assert scraper.window_seconds() >= 0.04
    assert scraper.first_mono is not None
    assert scraper.last_mono > scraper.first_mono
    records = [json.loads(line)
               for line in series.read_text().splitlines()]
    assert all("mono" in r and "ts" in r for r in records)
    assert records[-1]["mono"] > records[0]["mono"]


# ----------------------------------------------------- overhead (slow)
@pytest.mark.slow
@pytest.mark.usefixtures("tmp_state_dir")
def test_engine_throughput_armed_vs_unarmed_within_noise():
    """Armed stepstats does O(1) host bookkeeping per supervisor-loop
    iteration, never per-token device work — tok/s must stay within
    noise of the unarmed engine (generous CPU-CI bound; the bench
    harness's phase-breakdown fields carry the TPU-side check)."""
    from skypilot_tpu.serve.decode_engine import DecodeEngine

    cfg, params = _tiny_llm()

    def run():
        engine = DecodeEngine(cfg, params, slots=4, max_seq=96,
                              prefill_chunk=16).start()
        try:
            engine.warmup()
            t0 = time.perf_counter()
            reqs = [engine.submit([1 + i, 2, 3, 4], max_tokens=24)
                    for i in range(8)]
            total = sum(len(r.result(timeout=600)) for r in reqs)
            return total / (time.perf_counter() - t0)
        finally:
            engine.shutdown()

    cold = run()                   # warm the jit caches once, discard
    del cold
    unarmed = run()
    stepstats.arm(ring=1024, sync_every=8)
    stepstats.reset()
    try:
        armed_rate = run()
        snap = stepstats.snapshot()
    finally:
        stepstats.disarm()
        stepstats.reset()
    assert snap["steps"] > 0       # the armed leg measured something
    assert armed_rate >= 0.5 * unarmed, (armed_rate, unarmed)
