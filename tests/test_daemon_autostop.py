"""On-host daemon (skylet analog): autostop enforcement + job
reconciliation, end-to-end on the local provider.

The headline behavior (VERDICT r1 item 3): a cluster launched with
``-i 0`` stops ITSELF after its job finishes, with zero client
involvement — the daemon is a detached process, exactly like the
reference's AutostopEvent (sky/skylet/events.py:90).
"""
import json
import os
import pathlib
import time

import pytest

from skypilot_tpu import core, execution, global_user_state
from skypilot_tpu.agent import daemon as daemon_lib
from skypilot_tpu.agent import job_lib
from skypilot_tpu.agent import tpu_health
from skypilot_tpu.resources import Resources
from skypilot_tpu.status_lib import ClusterStatus
from skypilot_tpu.task import Task


def _local_res():
    return Resources(cloud="local")


def _wait(pred, timeout=20, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def live_daemon(tmp_state_dir, monkeypatch):
    """Enable the real detached daemon with a fast event loop."""
    monkeypatch.setenv("STPU_DISABLE_DAEMON", "0")
    monkeypatch.setenv("STPU_DAEMON_INTERVAL", "0.2")
    yield tmp_state_dir


# --------------------------------------------------------------- e2e
def test_autostop_stops_idle_cluster_without_client(live_daemon):
    """launch -i 0 → job finishes → cluster reaches STOPPED by itself."""
    task = Task("quick", run="echo done")
    task.set_resources(_local_res())
    job_id, handle = execution.launch(
        task, cluster_name="t-auto", detach_run=True, stream_logs=False,
        idle_minutes_to_autostop=0)
    pid_path = pathlib.Path(handle.head_home) / ".stpu_agent" / \
        "daemon.pid"
    # (With -i 0 the daemon may stop the cluster within one tick of the
    # job ending, so pid_path existing is racy to observe; the stop
    # itself — below — is the proof the daemon ran.)

    # No further client calls: the daemon notices idleness and stops the
    # cluster via the provider API.
    from skypilot_tpu.provision import local as local_provider

    def provider_stopped():
        statuses = local_provider.query_instances("t-auto", {})
        return statuses and set(statuses.values()) == {"stopped"}
    assert _wait(provider_stopped, timeout=30), \
        "daemon never stopped the idle cluster"

    # Client discovers it through normal status refresh (provider truth).
    records = core.status(["t-auto"], refresh=True)
    assert records[0]["status"] == ClusterStatus.STOPPED
    # Daemon exits once its cluster is down.
    assert _wait(lambda: not pid_path.exists(), timeout=10)


def test_autostop_down_terminates_cluster(live_daemon):
    """-i 0 --down → the cluster removes itself entirely."""
    task = Task("quick", run="echo done")
    task.set_resources(_local_res())
    _, handle = execution.launch(
        task, cluster_name="t-down", detach_run=True, stream_logs=False,
        idle_minutes_to_autostop=0, down=True)
    cluster_dir = pathlib.Path(handle.head_home).parent
    assert _wait(lambda: not cluster_dir.exists(), timeout=30), \
        "daemon never terminated the idle cluster"
    records = core.status(["t-down"], refresh=True)
    assert records == [] or records[0]["status"] is None


def test_no_autostop_without_config(live_daemon):
    """Without -i the daemon must leave the cluster alone."""
    task = Task("quick", run="echo done")
    task.set_resources(_local_res())
    _, handle = execution.launch(
        task, cluster_name="t-stay", detach_run=True, stream_logs=False)
    pid_path = pathlib.Path(handle.head_home) / ".stpu_agent" / \
        "daemon.pid"
    assert _wait(pid_path.exists)
    time.sleep(1.5)  # several daemon ticks
    from skypilot_tpu.provision import local as local_provider
    statuses = local_provider.query_instances("t-stay", {})
    assert set(statuses.values()) == {"running"}
    core.down("t-stay")
    assert _wait(lambda: not pid_path.exists(), timeout=10)


# ------------------------------------------------- in-process daemon units
def _make_agent_home(tmp_path, cluster="c1"):
    home = tmp_path / "host0"
    agent = home / ".stpu_agent"
    agent.mkdir(parents=True)
    (agent / "cluster.json").write_text(json.dumps({
        "cluster_name": cluster, "provider_name": "local",
        "stpu_home": os.environ.get("STPU_HOME", str(tmp_path / ".stpu")),
    }))
    return home


def test_daemon_waits_while_job_running(tmp_state_dir, tmp_path):
    home = _make_agent_home(tmp_path)
    (home / ".stpu_agent" / "autostop.json").write_text(
        json.dumps({"idle_minutes": 0, "down": False,
                    "set_at": time.time() - 60}))
    jid = job_lib.add_job("j", "u", "ts", "", home=str(home))
    job_lib.set_status(jid, job_lib.JobStatus.RUNNING, home=str(home))
    d = daemon_lib.Daemon(home=str(home), interval=0.1)
    assert d.check_autostop() is False  # busy cluster: no stop

    job_lib.set_status(jid, job_lib.JobStatus.SUCCEEDED, home=str(home))
    # With idle_minutes=5 it must NOT fire right after the job ends:
    # the recent end_at resets the idle clock.
    (home / ".stpu_agent" / "autostop.json").write_text(
        json.dumps({"idle_minutes": 5, "down": False,
                    "set_at": time.time() - 600}))
    assert d.check_autostop() is False


def test_daemon_reconciles_dead_gang_driver(tmp_state_dir, tmp_path):
    """RUNNING job whose driver pid is gone → FAILED (skylet's job-state
    reconciliation)."""
    home = _make_agent_home(tmp_path)
    jid = job_lib.add_job("j", "u", "ts", "", home=str(home))
    job_lib.set_status(jid, job_lib.JobStatus.RUNNING, home=str(home))
    job_lib.set_pid(jid, 2 ** 22 + 12345, home=str(home))  # surely dead
    d = daemon_lib.Daemon(home=str(home), interval=0.1)
    d.reconcile_jobs()
    assert job_lib.get_job(jid, home=str(home))["status"] == "FAILED"


def test_daemon_leaves_live_jobs_alone(tmp_state_dir, tmp_path):
    home = _make_agent_home(tmp_path)
    jid = job_lib.add_job("j", "u", "ts", "", home=str(home))
    job_lib.set_status(jid, job_lib.JobStatus.RUNNING, home=str(home))
    job_lib.set_pid(jid, os.getpid(), home=str(home))  # alive
    d = daemon_lib.Daemon(home=str(home), interval=0.1)
    d.reconcile_jobs()
    assert job_lib.get_job(jid, home=str(home))["status"] == "RUNNING"


# ----------------------------------------------------------- health probe
def test_health_probe_cpu_host_passes():
    report = tpu_health.probe(expected_chips=0)
    assert report["ok"]


def test_health_probe_missing_chips_fails(monkeypatch):
    monkeypatch.setattr(tpu_health, "count_local_chips", lambda: 0)
    report = tpu_health.probe(expected_chips=4)
    assert not report["ok"]
    assert "expected 4" in report["detail"]


def test_health_report_written(tmp_path):
    path = tpu_health.write_report(tpu_health.probe(0),
                                   home=str(tmp_path))
    assert json.loads(path.read_text())["ok"]
